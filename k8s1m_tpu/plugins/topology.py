"""PodTopologySpread + InterPodAffinity as count-table kernels.

Semantics (upstream parity, with documented divergences):

- Spread filter (whenUnsatisfiable=DoNotSchedule): placing the pod in
  domain d must keep ``count(d) + self - min_over_domains(count)`` within
  maxSkew; nodes missing the topology key fail the constraint.  Divergence:
  the global minimum is taken over all domains that currently contain at
  least one schedulable node, not over the pod's node-affinity-filtered
  subset (upstream computes the min after NodeAffinity pre-filtering).
- Spread score: constraints of both modes score; per constraint the least
  crowded domain gets 100 and the most crowded 0 (linear in count), then
  constraints average.  Upstream's normalization differs in shape but
  ranks domains identically (monotone decreasing in matching-pod count).
- Affinity required: a domain must contain a pod matching the term; the
  bootstrap exception (upstream's "no pod in the cluster matches" rule for
  self-matching terms) admits the first replica anywhere.
- Anti-affinity required: the domain must contain no matching pod, and —
  symmetry — no existing pod whose own required anti-affinity term matches
  the incoming pod may share a domain with it (own_* tables).
- Affinity score: preferred terms contribute weight x matching-pod-count
  (negated for anti), linearly rescaled to [0, 100] by the batch-static
  bound (see plugins/scores.py module doc for why static bounds).

The count tables make all of this O(B x N) gathers instead of upstream's
O(pods x nodes) selector walks — config 4 of BASELINE.json is the point.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from k8s1m_tpu.config import (
    SPREAD_DO_NOT_SCHEDULE,
    TOPO_HOSTNAME,
    TOPO_REGION,
    TOPO_ZONE,
)
from k8s1m_tpu.snapshot.constraints import ConstraintState
from k8s1m_tpu.snapshot.node_table import NodeTable
from k8s1m_tpu.snapshot.pod_encoding import PodBatch

# Python int, NOT jnp.int32: a module-level device array becomes a live
# buffer that jax captures as an executable *parameter* when other traces
# embed an equal constant, and the pjit fast path then drops it on cached
# re-execution ("supplied 66 buffers but compiled program expected 67").
_BIG = 1 << 30


@struct.dataclass
class TopoStats:
    """Batch-global reductions over the count tables (the prologue)."""

    spread_min: jax.Array   # i32[3, C] min count per topo granularity
    spread_max: jax.Array   # i32[3, C]
    tgt_max: jax.Array      # i32[A] max count over the term's domains
    tgt_total: jax.Array    # i32[A] total matching pods cluster-wide


def _domain_presence(table: NodeTable, size: int, ids, axis_name=None):
    present = jnp.zeros((size,), jnp.int32).at[ids].max(table.valid.astype(jnp.int32))
    if axis_name is not None:
        present = lax.pmax(present, axis_name)
    return present.at[0].set(0)  # domain 0 = "label missing", never a domain


def _masked_min(tab, present):  # tab [C, D], present [D]
    m = jnp.where(present[None, :] > 0, tab, _BIG).min(axis=1)
    return jnp.where(m == _BIG, 0, m)


def _masked_max(tab, present):
    return jnp.where(present[None, :] > 0, tab, 0).max(axis=1)


def prologue(
    table: NodeTable,
    cons: ConstraintState,
    *,
    axis_name: str | None = None,
) -> TopoStats:
    """Global reductions before the chunk scan.  Under shard_map, pass the
    node-shard axis name so node-domain reductions cross shards."""
    valid = table.valid
    node_present = valid.astype(jnp.int32)

    def node_min(tab):
        m = jnp.where(node_present[None, :] > 0, tab, _BIG).min(axis=1)
        if axis_name is not None:
            m = lax.pmin(m, axis_name)
        return jnp.where(m == _BIG, 0, m)

    def node_max(tab):
        m = jnp.where(node_present[None, :] > 0, tab, 0).max(axis=1)
        if axis_name is not None:
            m = lax.pmax(m, axis_name)
        return m

    zone_present = _domain_presence(table, cons.spread_zone.shape[1], table.zone, axis_name)
    region_present = _domain_presence(table, cons.spread_region.shape[1], table.region, axis_name)

    spread_min = jnp.stack([
        node_min(cons.spread_node),
        _masked_min(cons.spread_zone, zone_present),
        _masked_min(cons.spread_region, region_present),
    ])
    spread_max = jnp.stack([
        node_max(cons.spread_node),
        _masked_max(cons.spread_zone, zone_present),
        _masked_max(cons.spread_region, region_present),
    ])

    tgt_max = jnp.maximum(
        node_max(cons.tgt_node),
        jnp.maximum(
            _masked_max(cons.tgt_zone, zone_present),
            _masked_max(cons.tgt_region, region_present),
        ),
    )
    tgt_node_total = cons.tgt_node.sum(axis=1)
    if axis_name is not None:
        tgt_node_total = lax.psum(tgt_node_total, axis_name)
    tgt_total = tgt_node_total + cons.tgt_zone.sum(axis=1) + cons.tgt_region.sum(axis=1)
    return TopoStats(
        spread_min=spread_min, spread_max=spread_max,
        tgt_max=tgt_max, tgt_total=tgt_total,
    )


def _counts_for(node_tab, zone_tab, region_tab, slot, topo, table: NodeTable):
    """Gather per-node domain counts for [B, S] (slot, topo) refs -> [B, S, N]."""
    cnt_node = jnp.take(node_tab, slot, axis=0)                      # [B,S,N]
    cnt_zone = jnp.take(
        jnp.take(zone_tab, slot, axis=0), table.zone, axis=-1
    )
    cnt_region = jnp.take(
        jnp.take(region_tab, slot, axis=0), table.region, axis=-1
    )
    t = topo[:, :, None]
    cnt = jnp.where(
        t == TOPO_HOSTNAME, cnt_node,
        jnp.where(t == TOPO_ZONE, cnt_zone, cnt_region),
    )
    domain_ok = jnp.where(
        t == TOPO_HOSTNAME, True,
        jnp.where(
            t == TOPO_ZONE, (table.zone != 0)[None, None, :],
            (table.region != 0)[None, None, :],
        ),
    )
    return cnt, domain_ok


def _stat_for(stat, slot, topo):
    """Select a [3, C] per-topo stat for [B, S] refs -> [B, S]."""
    by_topo = jnp.take(stat, slot, axis=1)                            # [3,B,S]
    t = topo[None, :, :]
    sel = jnp.where(
        t == TOPO_HOSTNAME, by_topo[0:1],
        jnp.where(t == TOPO_ZONE, by_topo[1:2], by_topo[2:3]),
    )
    return sel[0]


def filter_and_score(
    table: NodeTable,
    batch: PodBatch,
    cons: ConstraintState,
    stats: TopoStats,
    spread_weight: float,
    ipa_weight: float,
):
    """(mask bool[B, N], score i32[B, N]) over one node chunk.

    A zero ``spread_weight`` / ``ipa_weight`` skips that plugin's
    *scoring* arithmetic at trace time — the weights arrive as static
    Python ints from the Profile — while the hard-constraint filtering
    (spread maxSkew, required [anti-]affinity, the symmetry mask)
    always runs: degraded overload modes (k8s1m_tpu/loadshed) trade
    placement quality, never correctness.
    """
    n = table.num_rows

    # ---- topology spread ----
    cnt, domain_ok = _counts_for(
        cons.spread_node, cons.spread_zone, cons.spread_region,
        batch.spread_cid, batch.spread_topo, table,
    )                                                                 # [B,S,N]
    min_c = _stat_for(stats.spread_min, batch.spread_cid, batch.spread_topo)
    self_inc = batch.spread_self.astype(jnp.int32)
    skew_ok = (cnt + self_inc[:, :, None] - min_c[:, :, None]) <= (
        batch.spread_max_skew[:, :, None]
    )
    hard = batch.spread_valid & (batch.spread_mode == SPREAD_DO_NOT_SCHEDULE)
    spread_mask = (~hard[:, :, None] | (domain_ok & skew_ok)).all(axis=1)

    spread_score = None
    if spread_weight:
        # score: least-crowded domain 100, most-crowded 0, avg over refs.
        max_c = _stat_for(
            stats.spread_max, batch.spread_cid, batch.spread_topo
        )
        denom = jnp.maximum(max_c - min_c, 1)[:, :, None]
        s_ref = 100.0 * (max_c[:, :, None] - cnt) / denom
        s_ref = jnp.where(domain_ok, jnp.clip(s_ref, 0.0, 100.0), 0.0)
        live = batch.spread_valid
        num_refs = jnp.maximum(live.sum(axis=1), 1)
        spread_score = (
            (s_ref * live[:, :, None]).sum(axis=1) / num_refs[:, None]
        )

    # ---- inter-pod affinity: the pod's own terms ----
    tcnt, t_domain_ok = _counts_for(
        cons.tgt_node, cons.tgt_zone, cons.tgt_region,
        batch.ipa_tid, batch.ipa_topo, table,
    )                                                                 # [B,A,N]
    total = jnp.take(stats.tgt_total, batch.ipa_tid)                  # [B,A]
    bootstrap = (total == 0) & batch.ipa_self
    req_aff_ok = t_domain_ok & ((tcnt > 0) | bootstrap[:, :, None])
    req_anti_ok = ~t_domain_ok | (tcnt == 0)
    live_req = batch.ipa_valid & batch.ipa_required
    term_ok = jnp.where(
        (live_req & ~batch.ipa_anti)[:, :, None], req_aff_ok,
        jnp.where((live_req & batch.ipa_anti)[:, :, None], req_anti_ok, True),
    )
    ipa_mask = term_ok.all(axis=1)

    # symmetry: existing pods' required anti-affinity (own_* only contains
    # required-anti owners) blocks domains for pods their selector matches.
    ocnt, o_domain_ok = _counts_for(
        cons.own_node, cons.own_zone, cons.own_region,
        batch.iinc_tid, batch.iinc_topo, table,
    )                                                                 # [B,AI,N]
    sym_ok = (~batch.iinc_valid[:, :, None] | ~o_domain_ok | (ocnt == 0)).all(axis=1)
    ipa_mask = ipa_mask & sym_ok

    ipa_score = None
    if ipa_weight:
        # preferred terms: weight x count, rescaled by the static bound.
        pref = batch.ipa_valid & ~batch.ipa_required
        sign = jnp.where(batch.ipa_anti, -1, 1) * batch.ipa_weight    # [B,A]
        raw = (jnp.where(pref[:, :, None] & t_domain_ok, tcnt, 0)
               * sign[:, :, None]).sum(axis=1)                        # [B,N]
        bound = (
            jnp.abs(batch.ipa_weight)
            * jnp.take(stats.tgt_max, batch.ipa_tid) * pref
        ).sum(axis=1)                                                 # [B]
        has_pref = pref.any(axis=1)
        ipa_score = jnp.where(
            has_pref[:, None],
            50.0 + 50.0 * raw / jnp.maximum(bound, 1)[:, None],
            0.0,
        )
        ipa_score = jnp.clip(ipa_score, 0.0, 100.0)

    mask = spread_mask & ipa_mask
    score = jnp.zeros(mask.shape, jnp.int32)
    if spread_weight:
        score += jnp.floor(spread_score).astype(jnp.int32) * int(spread_weight)
    if ipa_weight:
        score += jnp.floor(ipa_score).astype(jnp.int32) * int(ipa_weight)
    return mask, score
