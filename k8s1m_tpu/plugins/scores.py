"""Score plugins as tensor kernels over (pod batch x node chunk).

Each returns f32[B, N] in [0, 100] (higher = better), mirroring upstream
scheduling-framework Score plugins; the registry applies the default-profile
weights and sums, which is exactly the per-node total the fork publishes as
NodePluginScoresState for DistPermit (reference
dist-scheduler/pkg/distpermit/distpermit.go:51-56).

Known divergence from upstream, by design: plugins whose upstream
NormalizeScore divides by the *observed* max across nodes (TaintToleration,
NodeAffinity) here normalize by a *static* per-pod bound instead (max
possible count / sum of term weights).  Node ordering within each plugin is
identical; only the inter-plugin mixing ratio can differ.  A static bound
keeps the kernel single-pass over node chunks — the observed max would need
a second full pass over 1M nodes per batch.  The differential oracle
implements these exact semantics.
"""

from __future__ import annotations

import jax.numpy as jnp

from k8s1m_tpu.config import EFFECT_PREFER_NO_SCHEDULE, NONE_ID
from k8s1m_tpu.ops.label_match import ResolvedKeys, match_expressions
from k8s1m_tpu.snapshot.node_table import NodeTable
from k8s1m_tpu.snapshot.pod_encoding import PodBatch


def least_allocated(table: NodeTable, batch: PodBatch):
    """NodeResourcesFit LeastAllocated: mean over {cpu, mem} of free/alloc."""
    cpu_after = table.cpu_req[None, :] + batch.cpu[:, None]
    mem_after = table.mem_req[None, :] + batch.mem[:, None]
    alloc_cpu = jnp.maximum(table.cpu_alloc, 1)[None, :]
    alloc_mem = jnp.maximum(table.mem_alloc, 1)[None, :]
    cpu_score = (alloc_cpu - cpu_after) / alloc_cpu
    mem_score = (alloc_mem - mem_after) / alloc_mem
    return 50.0 * (jnp.clip(cpu_score, 0.0) + jnp.clip(mem_score, 0.0))


def balanced_allocation(table: NodeTable, batch: PodBatch):
    """NodeResourcesBalancedAllocation: 100 * (1 - std of resource fractions).

    For two resources the standard deviation is |f_cpu - f_mem| / 2.
    """
    alloc_cpu = jnp.maximum(table.cpu_alloc, 1)[None, :]
    alloc_mem = jnp.maximum(table.mem_alloc, 1)[None, :]
    f_cpu = jnp.clip((table.cpu_req[None, :] + batch.cpu[:, None]) / alloc_cpu, 0.0, 1.0)
    f_mem = jnp.clip((table.mem_req[None, :] + batch.mem[:, None]) / alloc_mem, 0.0, 1.0)
    return 100.0 * (1.0 - jnp.abs(f_cpu - f_mem) / 2.0)


def taint_toleration(table: NodeTable, batch: PodBatch):
    """TaintToleration score: fewer untolerated PreferNoSchedule taints is
    better.  Static-bound normalization over taint_slots (see module doc)."""
    b = batch.batch
    n, ts = table.taint_id.shape
    soft = (table.taint_id != NONE_ID) & (
        table.taint_effect == EFFECT_PREFER_NO_SCHEDULE
    )
    tol = jnp.take(batch.tolerated, table.taint_id.reshape(-1), axis=1).reshape(b, n, ts)
    count = (soft[None, :, :] & ~tol).sum(axis=-1)
    return 100.0 * (1.0 - count / ts)


def node_affinity_score(table: NodeTable, batch: PodBatch, resolved: ResolvedKeys):
    """NodeAffinity preferred terms: sum of matched term weights, normalized
    by the pod's total preferred weight (static bound, see module doc)."""
    term_match, has_expr = match_expressions(
        resolved,
        batch.pref_expr_valid,
        batch.pref_qidx,
        batch.pref_op,
        batch.pref_vals,
        batch.pref_num,
    )  # [B, P, N]
    live = batch.pref_term_valid & has_expr
    w = jnp.where(live, batch.pref_weight, 0)              # [B, P]
    matched = (term_match & live[:, :, None]) * w[:, :, None]
    total = jnp.maximum(w.sum(axis=1), 1)                  # [B]
    return 100.0 * matched.sum(axis=1) / total[:, None]
