"""Plugin profile: which filter/score kernels run and with what weights.

Mirrors the reference's KubeSchedulerConfiguration profile — default
filter/score set, DefaultPreemption disabled, DistPermit replaced by the
engine's on-device global argmax (reference
terraform/kubernetes/dist-scheduler.tf:551-570).  Weights are the upstream
defaults for the plugins the BASELINE.json configs exercise.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from k8s1m_tpu.ops.label_match import ResolvedKeys, resolve_query_keys
from k8s1m_tpu.plugins import filters, scores
from k8s1m_tpu.snapshot.node_table import NodeTable
from k8s1m_tpu.snapshot.pod_encoding import PodBatch


@dataclasses.dataclass(frozen=True)
class Profile:
    """Score weights (upstream defaults); 0 disables a plugin.

    Weights are integers like upstream's plugin weights — fractional
    values would silently truncate in the int32 score accumulation."""

    least_allocated: int = 1
    balanced_allocation: int = 1
    taint_toleration: int = 3
    node_affinity: int = 2
    topology_spread: int = 2
    interpod_affinity: int = 2

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not isinstance(v, int) or v < 0:
                raise ValueError(
                    f"Profile.{f.name} must be a non-negative int, got {v!r}"
                )


def default_profile() -> Profile:
    return Profile()


def degraded_profile(profile: Profile) -> Profile:
    """``profile`` with the expensive constraint *scoring* dropped —
    PodTopologySpread and InterPodAffinity become filter-only (their
    hard constraints still mask; see topology.filter_and_score).  The
    overload degraded mode's plugin set (k8s1m_tpu/loadshed): placement
    quality is traded for cycle time, feasibility semantics never are.
    """
    return dataclasses.replace(profile, topology_spread=0, interpod_affinity=0)


def score_and_filter(
    table: NodeTable,
    batch: PodBatch,
    profile: Profile,
    constraints=None,
    stats=None,
):
    """One fused pass over a node chunk: (mask bool[B,N], score i32[B,N]).

    ``constraints`` is the ConstraintState chunk for topology-spread /
    inter-pod-affinity (with ``stats`` the batch prologue); None disables
    those plugins (configs 1-2 of BASELINE.json).
    """
    resolved = resolve_query_keys(
        table.label_key, table.label_val, table.label_num, batch.qkey
    )
    mask = filters.feasible_mask(table, batch, resolved)

    # Each plugin emits [0, 100] and is floored to an integer before
    # weighting, like upstream's int64 framework scores — integer totals are
    # what makes the random tie-break exact (see ops/priority.py).
    def w(weight, s):
        return jnp.floor(s).astype(jnp.int32) * int(weight)

    score = jnp.zeros(mask.shape, jnp.int32)
    if profile.least_allocated:
        score += w(profile.least_allocated, scores.least_allocated(table, batch))
    if profile.balanced_allocation:
        score += w(profile.balanced_allocation, scores.balanced_allocation(table, batch))
    if profile.taint_toleration:
        score += w(profile.taint_toleration, scores.taint_toleration(table, batch))
    if profile.node_affinity:
        score += w(
            profile.node_affinity, scores.node_affinity_score(table, batch, resolved)
        )
    if constraints is not None:
        from k8s1m_tpu.plugins import topology

        tmask, tscore = topology.filter_and_score(
            table, batch, constraints, stats,
            profile.topology_spread, profile.interpod_affinity,
        )
        mask = mask & tmask
        score += tscore
    return mask, score
