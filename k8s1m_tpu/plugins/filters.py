"""Filter plugins as tensor kernels over (pod batch x node chunk).

Each function returns a bool mask with shape [B, N] (True = node passes for
pod).  They replace the Go scheduling-framework Filter plugins the forked
scheduler runs per pod per node (~560us/pod of CPU across the fleet,
reference README.adoc:786-787).  All masks AND together in feasible_mask;
XLA fuses the whole thing into one pass over the node chunk.

Upstream plugin parity:
- fits_resources   <- NodeResourcesFit (cpu, memory, pod count)
- node_name        <- NodeName
- tolerates_taints <- TaintToleration (+NodeUnschedulable via the
                      synthetic unschedulable taint, see node_table.py)
- node_affinity    <- NodeAffinity required terms + spec.nodeSelector
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from k8s1m_tpu.config import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    NONE_ID,
)
from k8s1m_tpu.ops.label_match import ResolvedKeys, match_expressions, resolve_query_keys
from k8s1m_tpu.snapshot.node_table import NodeTable
from k8s1m_tpu.snapshot.pod_encoding import PodBatch


def _statically_empty(x) -> bool:
    """True when ``x`` is a HOST constant with no live entries — the
    excluded-packed-group case (``unpack_pod_batch`` materializes
    absent groups as numpy zeros precisely so this check can see them
    inside a trace).  Skipping the plugin then is a pure no-op on the
    math (all-False validity masks already make it pass-through) but
    keeps the [B, S, N] constant chain out of the program: XLA-CPU
    otherwise constant-folds it at 26-64s per batch bucket
    (slow_operation_alarm on plugins/filters.py), which is most of a
    cold sched_bench/soak start.  Deliberately numpy-only: tracers must
    trace, and probing a concrete *device* array here would force a
    device->host sync on every eager call for nothing.
    """
    return isinstance(x, np.ndarray) and not x.any()


def fits_resources(table: NodeTable, batch: PodBatch):
    """NodeResourcesFit: requests fit in allocatable-minus-requested."""
    free_cpu, free_mem, free_pods = table.free()
    return (
        (batch.cpu[:, None] <= free_cpu[None, :])
        & (batch.mem[:, None] <= free_mem[None, :])
        & (free_pods[None, :] >= 1)
    )


def node_name(table: NodeTable, batch: PodBatch):
    """NodeName: spec.nodeName, when set, must equal the node's name."""
    unset = batch.node_name_id == NONE_ID
    return unset[:, None] | (batch.node_name_id[:, None] == table.name_id[None, :])


def tolerates_taints(table: NodeTable, batch: PodBatch):
    """TaintToleration: every hard taint on the node must be tolerated.

    The toleration evaluation already happened on the host per distinct
    taint triple (PodBatch.tolerated); here it's a gather + reduce.
    """
    b = batch.batch
    n, ts = table.taint_id.shape
    hard = (table.taint_id != NONE_ID) & (
        (table.taint_effect == EFFECT_NO_SCHEDULE)
        | (table.taint_effect == EFFECT_NO_EXECUTE)
    )
    if _statically_empty(batch.tolerated):
        # Tol group absent: no pod tolerates anything, so a node passes
        # iff it carries no hard taint — same result, no [B, N, TS]
        # gather for XLA to fold.
        return jnp.broadcast_to(~hard.any(axis=-1)[None, :], (b, n))
    # [B, N*TS] gather of host-evaluated results, back to [B, N, TS].
    tol = jnp.take(batch.tolerated, table.taint_id.reshape(-1), axis=1)
    tol = tol.reshape(b, n, ts)
    return ~(hard[None, :, :] & ~tol).any(axis=-1)


def node_affinity(table: NodeTable, batch: PodBatch, resolved: ResolvedKeys):
    """NodeAffinity required terms (OR of ANDed terms) + spec.nodeSelector."""
    parts = []
    if not _statically_empty(batch.sel_valid):
        # nodeSelector: ANDed exact matches.  (All-False sel_valid is
        # pass-through; skipped when statically absent.)
        f = jnp.take(resolved.found, batch.sel_qidx, axis=0)   # [B, S, N]
        v = jnp.take(resolved.val, batch.sel_qidx, axis=0)
        sel_ok = f & (v == batch.sel_val[:, :, None])
        parts.append((sel_ok | ~batch.sel_valid[:, :, None]).all(axis=1))

    if not _statically_empty(batch.req_term_valid):
        # required affinity: OR over terms.  (No live terms means
        # aff_pass is all-True; skipped when statically absent.)
        term_match, has_expr = match_expressions(
            resolved,
            batch.req_expr_valid,
            batch.req_qidx,
            batch.req_op,
            batch.req_vals,
            batch.req_num,
        )  # term_match: [B, T, N]
        live = batch.req_term_valid & has_expr         # empty term matches nothing
        any_term = (term_match & live[:, :, None]).any(axis=1)
        has_terms = batch.req_term_valid.any(axis=1)
        parts.append(jnp.where(has_terms[:, None], any_term, True))

    if not parts:
        n = table.name_id.shape[0]
        return jnp.ones((batch.batch, n), jnp.bool_)
    out = parts[0]
    for p in parts[1:]:
        out = out & p
    return out


def feasible_mask(table: NodeTable, batch: PodBatch, resolved: ResolvedKeys | None = None):
    """AND of all filter plugins, plus row validity. bool[B, N]."""
    if resolved is None:
        resolved = resolve_query_keys(
            table.label_key, table.label_val, table.label_num, batch.qkey
        )
    mask = table.valid[None, :]
    mask = mask & fits_resources(table, batch)
    mask = mask & node_name(table, batch)
    mask = mask & tolerates_taints(table, batch)
    mask = mask & node_affinity(table, batch, resolved)
    return mask & batch.valid[:, None]
