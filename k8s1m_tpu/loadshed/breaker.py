"""Circuit breaker around TPU cycle dispatch.

A wedged device runtime (driver hang, injected ``stall``, a mesh peer
gone) must not take scheduling down with it: after
``failure_threshold`` consecutive dispatch failures the breaker OPENs
and the coordinator stops launching device waves, scheduling small
batches through the host-side ``oracle/`` reference scheduler instead
— slower, but byte-identical placements and never a full stop.  After
``cooldown_cycles`` open cycles the breaker goes HALF_OPEN and lets
exactly one probe wave through; success closes it, failure re-opens
with a fresh cooldown.

Time is counted in *cycles*, not seconds, so the breaker replays
identically on a virtual clock (tools/overload_drill.py tier-1 smoke)
and in wall-clock soaks alike.

Scope: failures are observed at *dispatch* (the launch raises — the
faultline ``stall`` kind, driver rejections) and successes at *retire*
(the wave's results came back).  A runtime that accepts the async
dispatch and then never completes blocks the caller inside the
device fetch, where no portable timeout exists — that class needs an
external watchdog (``tools/with_deadline.py`` process-level deadlines),
not this breaker.  Under a deep pipeline an open can lag dispatch
failures by up to ``depth`` retires (old waves retiring successfully
reset the consecutive count) — by design: a device draining real work
is not yet dead.

Metrics: ``breaker_state{component}`` (0 closed, 1 open, 2 half-open),
``breaker_transitions_total{component,from,to}``,
``breaker_fallback_binds_total``.
"""

from __future__ import annotations

import dataclasses
import logging

from k8s1m_tpu.obs.metrics import Counter, Gauge

log = logging.getLogger("k8s1m.loadshed")

CLOSED, OPEN, HALF_OPEN = 0, 1, 2
BREAKER_STATE_NAMES = ("closed", "open", "half_open")

_BREAKER_STATE = Gauge(
    "breaker_state",
    "Cycle-dispatch circuit breaker: 0 closed, 1 open, 2 half-open",
    ("component",),
)
_BREAKER_TRANSITIONS = Counter(
    "breaker_transitions_total",
    "Circuit breaker transitions",
    ("component", "from", "to"),
)
FALLBACK_BINDS = Counter(
    "breaker_fallback_binds_total",
    "Pods bound via the host-side oracle while the breaker was open",
    (),
)


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    failure_threshold: int = 3   # consecutive dispatch failures to OPEN
    cooldown_cycles: int = 8     # open cycles before the half-open probe
    fallback_batch: int = 64     # pods per open-state oracle fallback wave

    def __post_init__(self):
        if self.failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if self.cooldown_cycles < 1:
            raise ValueError("cooldown_cycles must be >= 1")
        if self.fallback_batch < 1:
            raise ValueError("fallback_batch must be >= 1")


class CircuitBreaker:
    """CLOSED -> OPEN -> HALF_OPEN -> CLOSED, clocked in cycles.

    Protocol per cycle with a batch to dispatch:

    - ``allow()`` — True: launch the device wave, then report the
      outcome with ``record_success()`` / ``record_failure()``.
      False: the breaker is open; schedule the fallback batch instead.
    - In HALF_OPEN, ``allow()`` admits exactly one probe at a time;
      its outcome decides CLOSED vs a fresh OPEN cooldown.
    """

    def __init__(
        self,
        config: BreakerConfig | None = None,
        component: str = "coordinator.cycle",
    ):
        self.config = config or BreakerConfig()
        self.component = component
        self.state = CLOSED
        self._failures = 0
        self._open_cycles = 0
        self._probe_inflight = False
        _BREAKER_STATE.set(CLOSED, component=component)

    def _set_state(self, new: int) -> None:
        if new == self.state:
            return
        _BREAKER_TRANSITIONS.inc(
            component=self.component,
            **{
                "from": BREAKER_STATE_NAMES[self.state],
                "to": BREAKER_STATE_NAMES[new],
            },
        )
        log.warning(
            "%s breaker %s -> %s", self.component,
            BREAKER_STATE_NAMES[self.state], BREAKER_STATE_NAMES[new],
        )
        self.state = new
        _BREAKER_STATE.set(new, component=self.component)

    def allow(self) -> bool:
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            self._open_cycles += 1
            if self._open_cycles >= self.config.cooldown_cycles:
                self._set_state(HALF_OPEN)
                self._probe_inflight = True
                return True
            return False
        # HALF_OPEN: one probe at a time.
        if self._probe_inflight:
            return False
        self._probe_inflight = True
        return True

    def record_success(self) -> None:
        self._failures = 0
        if self.state == HALF_OPEN:
            self._probe_inflight = False
            self._set_state(CLOSED)
        # OPEN stays OPEN: a pre-failure wave retiring during the
        # open-state quiesce is not the probe — recovery goes through
        # the half-open protocol, never around it.

    def record_failure(self) -> None:
        self._probe_inflight = False
        if self.state == HALF_OPEN:
            self._open_cycles = 0
            self._set_state(OPEN)
            return
        self._failures += 1
        if self.state == CLOSED and (
            self._failures >= self.config.failure_threshold
        ):
            self._open_cycles = 0
            self._set_state(OPEN)
