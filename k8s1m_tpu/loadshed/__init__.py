"""loadshed: adaptive overload control, admission shedding, graceful
degradation.

PR 1 (faultline) made failures *visible* — conflict storms surface as
backoff backpressure, watch loss as resyncs.  This package makes the
system *react*: a health controller (``controller.py``) watches those
signals and drives three enforcement points —

1. **Admission control** — ``control/webhook.py`` answers 429 +
   ``Retry-After`` and ``Coordinator.submit_external`` raises
   ``Overloaded`` past the high watermark, shedding lowest-priority
   pods first (``ops/priority.py pod_priority_of``), with a hard
   ``queue_cap`` no priority can pass.
2. **Degraded scheduling modes** — the coordinator shrinks
   ``score_pct`` (the ``sample_rows_for`` path), drops the expensive
   PodTopologySpread / InterPodAffinity plugins to filter-only scoring
   (hard constraints always keep filtering), and widens batch windows,
   so binds/sec degrades gracefully instead of latency exploding.
3. **Circuit breaker** (``breaker.py``) — consecutive cycle-dispatch
   failures open it; while open, small batches fall back to the
   host-side ``oracle/`` scheduler (byte-identical placements), so
   scheduling never fully stops; half-open probes close it again.

All state is integer counters clocked in cycles — deterministic on a
virtual clock (tools/overload_drill.py) and in wall-clock soaks alike.
"""

from k8s1m_tpu.loadshed.breaker import (
    BREAKER_STATE_NAMES,
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerConfig,
    CircuitBreaker,
)
from k8s1m_tpu.loadshed.controller import (
    DEGRADED,
    HEALTHY,
    SHEDDING,
    STATE_NAMES,
    HealthController,
    LoadshedConfig,
    Overloaded,
    Signals,
)

__all__ = [
    "BREAKER_STATE_NAMES",
    "BreakerConfig",
    "CLOSED",
    "CircuitBreaker",
    "DEGRADED",
    "HALF_OPEN",
    "HEALTHY",
    "HealthController",
    "LoadshedConfig",
    "OPEN",
    "Overloaded",
    "SHEDDING",
    "STATE_NAMES",
    "Signals",
]
