"""The overload health controller: HEALTHY -> DEGRADED -> SHEDDING.

The reference survives 1M nodes by never letting a control-plane
component take more work than it can finish: mem_etcd keeps its API
minimal and alarms on slow ops (``AlertingHistogramTimer``, reference
store.rs:883-907), and dist-scheduler's ``percentageOfNodesToScore: 5``
is itself a *static* degradation knob baked into the production config
(reference README.adoc:525-531).  This module makes that posture
dynamic: a small state machine fed by the signals the coordinator
already exports — queue depth, backoff depth, bind-conflict rate, cycle
latency, watch-overflow resyncs — that tells the enforcement points how
much to give up:

- **HEALTHY**  — full plugin set, configured ``score_pct``, adaptive
  small-batch buckets, admit everything.
- **DEGRADED** — shrink ``score_pct`` to ``degraded_score_pct``, drop
  the PodTopologySpread / InterPodAffinity *scoring* (hard constraint
  filtering always stays — correctness is never degraded), widen batch
  windows (no small buckets: throughput over latency).  Admission still
  accepts everything below the hard queue cap.
- **SHEDDING** — everything DEGRADED does, plus admission control: pods
  below an adaptive priority floor are rejected (HTTP 429 +
  ``Retry-After`` at the webhook, ``Overloaded`` from
  ``Coordinator.submit_external``).  The floor climbs one priority
  level per still-overloaded tick and falls back when pressure clears,
  so the *lowest-priority* pods are always the ones shed — the same
  ordering contract as kube-apiserver priority-and-fairness.

Escalation is immediate (one bad tick), recovery is hysteretic: the
controller must see ``recover_cycles`` consecutive calm ticks (load
under ``queue_recover``) to step DOWN one state, so a load hovering at
a watermark cannot flap the whole stack between modes.

Everything is integer thresholds and counters — no RNG, no wall clock —
so a drill on a virtual clock replays the same state trajectory from
the same signal sequence (the faultline determinism contract extended
to overload).

Metrics: ``loadshed_state{controller}`` (0/1/2),
``loadshed_transitions_total{controller,from,to}``,
``admission_rejected_total{point,reason}`` (reason ``priority`` = under
the floor, ``cap`` = hard queue cap), ``degraded_cycles_total{mode}``.
"""

from __future__ import annotations

import dataclasses
import threading

from k8s1m_tpu.lint import guarded_by
from k8s1m_tpu.obs.metrics import Counter, Gauge

HEALTHY, DEGRADED, SHEDDING = 0, 1, 2
STATE_NAMES = ("healthy", "degraded", "shedding")

_STATE = Gauge(
    "loadshed_state",
    "Overload state: 0 healthy, 1 degraded, 2 shedding",
    ("controller",),
)
_TRANSITIONS = Counter(
    "loadshed_transitions_total",
    "Overload state transitions",
    ("controller", "from", "to"),
)
_REJECTED = Counter(
    "admission_rejected_total",
    "Pods rejected at admission, by enforcement point and reason",
    ("point", "reason"),
)
_DEGRADED_CYCLES = Counter(
    "degraded_cycles_total",
    "Scheduling waves run with degraded knobs, by mode",
    ("mode",),
)


class Overloaded(Exception):
    """Admission rejected under overload; carries the backoff hint the
    webhook maps onto an HTTP 429 ``Retry-After`` header."""

    def __init__(self, retry_after_s: float, reason: str = "priority"):
        super().__init__(
            f"admission shed ({reason}); retry after {retry_after_s:.1f}s"
        )
        self.retry_after_s = retry_after_s
        self.reason = reason


@dataclasses.dataclass
class Signals:
    """One tick's worth of overload evidence, sampled by the coordinator."""

    queue_depth: int = 0     # pending pods (queue + staged webhook intake)
    backoff_depth: int = 0   # pods waiting out a retry backoff
    conflicts: int = 0       # bind CAS conflicts since the last tick
    resyncs: int = 0         # watch-overflow relists since the last tick
    cycle_s: float = 0.0     # last completed cycle's wall time

    @property
    def load(self) -> int:
        return self.queue_depth + self.backoff_depth


@dataclasses.dataclass(frozen=True)
class LoadshedConfig:
    """Operator knobs (see README "Overload & degraded modes").

    Watermarks are in pods of *load* (queue + backoff depth); they must
    satisfy ``queue_recover < queue_degraded <= queue_shed <= queue_cap``.
    ``queue_cap`` is the hard bound admission enforces regardless of
    priority — the "coordinator queue stays under its cap" guarantee.
    """

    queue_degraded: int = 8192     # load >= this -> DEGRADED
    queue_shed: int = 16384        # load >= this -> SHEDDING (high watermark)
    queue_cap: int = 32768         # hard cap: reject every priority past it
    queue_recover: int = 2048      # hysteresis: a tick is calm below this
    recover_cycles: int = 8        # calm ticks per one-state step-down
    cycle_slow_s: float = float("inf")   # cycle p99 past this -> DEGRADED
    conflicts_degraded: int = 1 << 30    # conflicts/tick past this -> DEGRADED
    latency_window: int = 64       # cycle samples kept for the p99
    degraded_score_pct: int = 1    # score_pct while degraded/shedding
    retry_after_s: float = 1.0     # the 429 Retry-After hint

    def __post_init__(self):
        if not (
            0 <= self.queue_recover
            < self.queue_degraded
            <= self.queue_shed
            <= self.queue_cap
        ):
            raise ValueError(
                "want queue_recover < queue_degraded <= queue_shed <= "
                f"queue_cap, got {self.queue_recover}/{self.queue_degraded}"
                f"/{self.queue_shed}/{self.queue_cap}"
            )
        if self.recover_cycles < 1:
            raise ValueError("recover_cycles must be >= 1")
        if not 1 <= self.degraded_score_pct <= 100:
            raise ValueError(
                f"degraded_score_pct must be in [1, 100], "
                f"got {self.degraded_score_pct}"
            )


@guarded_by(
    # Everything the webhook handler threads and the cycle thread both
    # touch lives under the admission lock: the sampled load + intra-tick
    # admission count (the hard-cap arithmetic), the shedding floor and
    # priority bounds (written by tick, read by every admission), and the
    # state itself (read by admissions and the degraded-knobs switch).
    # The lint/guards.py audit raises on any access outside the lock.
    _load="_admit_lock",
    _admitted_since_tick="_admit_lock",
    _floor="_admit_lock",
    _prio_lo="_admit_lock",
    _prio_hi="_admit_lock",
    _prio_seen="_admit_lock",
    state="_admit_lock",
)
class HealthController:
    """The overload state machine; one per coordinator.

    ``tick(signals)`` once per scheduling cycle moves the state;
    ``admit(priority, point)`` is the admission predicate the webhook
    and ``submit_external`` consult.  Admissions between ticks count
    against the sampled load, so the ``queue_cap`` bound holds even
    when a burst lands entirely inside one cycle.
    """

    def __init__(
        self, config: LoadshedConfig | None = None, name: str = "coordinator"
    ):
        self.config = config or LoadshedConfig()
        self.name = name
        self.state = HEALTHY
        self._calm = 0
        self._load = 0
        self._admitted_since_tick = 0
        # Adaptive priority floor: pods with priority < floor are shed
        # while SHEDDING.  Bounds track the priorities actually offered,
        # so the floor can always climb high enough to bite and never
        # chases values nobody submits.  The bounds (and the floor) are
        # seeded by the FIRST offered priority, not by 0 — a workload
        # submitting only negative priorities must not find itself shed
        # entirely by a floor stuck at a level nobody ever offered.
        self._prio_lo = 0
        self._prio_hi = 0
        self._prio_seen = False
        self._floor = 0
        self.ticks = 0
        # Recent cycle wall times (newest latency_window samples).
        self._lat: list[float] = []
        # admit() runs concurrently from webhook handler threads; the
        # cap check-then-increment must be one atomic step or a burst
        # of parallel admissions overshoots the "hard" queue_cap.
        self._admit_lock = threading.Lock()
        _STATE.set(HEALTHY, controller=name)

    # ---- state machine -------------------------------------------------

    def _set_state(self, new: int) -> None:
        """State transition; caller must hold ``_admit_lock`` (state and
        the shedding floor are read concurrently by admissions)."""
        if new == self.state:
            return
        _TRANSITIONS.inc(
            controller=self.name,
            **{"from": STATE_NAMES[self.state], "to": STATE_NAMES[new]},
        )
        self.state = new
        _STATE.set(new, controller=self.name)
        if new < SHEDDING:
            self._floor = self._prio_lo   # stop shedding: admit all again

    def tick(self, signals: Signals) -> int:
        """Advance one cycle; returns the (possibly new) state."""
        self.ticks += 1
        cfg = self.config
        self._lat.append(signals.cycle_s)
        if len(self._lat) > cfg.latency_window:
            self._lat.pop(0)

        overloaded = signals.load >= cfg.queue_shed
        strained = (
            signals.load >= cfg.queue_degraded
            or self.cycle_p99() >= cfg.cycle_slow_s
            or signals.conflicts >= cfg.conflicts_degraded
            or signals.resyncs > 0
        )
        # The whole transition runs under the admission lock: webhook
        # handler threads read state/floor on every admission, and a
        # half-applied transition (state moved, floor not yet) would
        # leak exactly the burst the watermarks exist to stop.
        with self._admit_lock:
            self._load = signals.load
            self._admitted_since_tick = 0
            if overloaded:
                self._calm = 0
                self._set_state(SHEDDING)
                # Still at/above the high watermark: shed one priority
                # level deeper.  Deterministic — pure function of the
                # load series.
                self._floor = min(self._floor + 1, self._prio_hi)
            elif strained:
                self._calm = 0
                if self.state < DEGRADED:
                    self._set_state(DEGRADED)
            elif signals.load <= cfg.queue_recover:
                self._calm += 1
                if self._calm >= cfg.recover_cycles and self.state > HEALTHY:
                    # Hysteresis: one state per recover_cycles calm
                    # ticks, never a straight SHEDDING -> HEALTHY jump.
                    self._set_state(self.state - 1)
                    self._calm = 0
            else:
                # Between recover and degraded watermarks: hold.
                self._calm = 0
            return self.state

    def cycle_p99(self) -> float:
        if not self._lat:
            return 0.0
        s = sorted(self._lat)
        return s[min(len(s) - 1, int(len(s) * 0.99))]

    @property
    def degraded(self) -> bool:
        with self._admit_lock:
            return self.state != HEALTHY

    def current_state(self) -> int:
        """Locked state read for composing layers (tenancy's weighted-
        fair admission keys its enforcement on it; a bare ``.state``
        read would violate the declared lock discipline)."""
        with self._admit_lock:
            return self.state

    def lag_budget(self, full: int) -> int:
        """Degradation knob for fan-out tiers (store/watch_cache.py):
        the per-subscriber FIFO depth a consumer may lag before
        latest-only coalescing engages.  HEALTHY keeps the configured
        budget, DEGRADED quarters it, SHEDDING zeroes it (coalesce
        immediately, tier-wide).  Depth-triggered enforcement means the
        deepest-backlog — i.e. floodiest — watchers degrade first; this
        method just sets how hard the controller squeezes."""
        with self._admit_lock:
            s = self.state
        if s == SHEDDING:
            return 0
        if s == DEGRADED:
            return max(1, full // 4)
        return full

    # ---- admission -----------------------------------------------------

    def try_admit(
        self, priority: int = 0, point: str = "coordinator",
        *, floor: bool = True,
    ) -> str | None:
        """Admission predicate: None = admitted, else the rejection
        reason (``"cap"`` = hard queue bound, any priority; ``"priority"``
        = under the shedding floor — the client's cue to raise its
        PriorityClass rather than just back off).  Counts every accept
        against the load sampled at the last tick so ``queue_cap`` is a
        hard bound, not a per-tick approximation.

        ``floor=False`` keeps the hard cap but skips the adaptive
        priority floor — the tenancy layer's form (k8s1m_tpu/tenancy):
        it sheds proportionally by tenant instead of globally by
        priority, and priority's job moves to preemption."""
        with self._admit_lock:
            # Bounds tracking moved under the lock: concurrent admissions
            # used to lose min/max updates (the shedding floor could then
            # never climb high enough to bite) — found by the guard audit.
            if not self._prio_seen:
                # First offer seeds the band AND — outside SHEDDING —
                # the floor (floor at the observed minimum = admit
                # everything, the same level recovery resets to).
                self._prio_seen = True
                self._prio_lo = self._prio_hi = priority
                if self.state < SHEDDING:
                    self._floor = priority
            else:
                if priority < self._prio_lo:
                    self._prio_lo = priority
                    # The floor tracks the observed MINIMUM until a
                    # shedding episode actually escalates it: a high-
                    # priority first pod must not pre-arm the floor so
                    # that entering SHEDDING instantly sheds everything
                    # below it instead of one level per tick.
                    if self.state < SHEDDING:
                        self._floor = priority
                self._prio_hi = max(self._prio_hi, priority)
            if (
                self._load + self._admitted_since_tick
                >= self.config.queue_cap
            ):
                reason = "cap"
            elif floor and self.state == SHEDDING and priority < self._floor:
                reason = "priority"
            else:
                self._admitted_since_tick += 1
                return None
        _REJECTED.inc(point=point, reason=reason)
        return reason

    def admit(self, priority: int = 0, point: str = "coordinator") -> bool:
        """Boolean form of ``try_admit`` (the webhook's 429 gate)."""
        return self.try_admit(priority, point) is None

    def check_admit(self, priority: int = 0, point: str = "coordinator") -> None:
        """``try_admit`` that raises ``Overloaded`` (submit_external's
        form), carrying the real rejection reason."""
        reason = self.try_admit(priority, point)
        if reason is not None:
            raise Overloaded(self.config.retry_after_s, reason)

    def retry_after_s(self) -> float:
        return self.config.retry_after_s

    def note_degraded_cycle(self) -> None:
        """Called by the coordinator for every wave launched with
        degraded knobs (the ``degraded_cycles_total`` evidence)."""
        with self._admit_lock:
            mode = STATE_NAMES[self.state]
        _DEGRADED_CYCLES.inc(mode=mode)
