"""Static shape configuration for device-resident tables.

Everything that lands on the TPU has a static, padded shape: XLA traces the
scheduling step once per (TableSpec, PodSpec) bucket and reuses the
executable. Growing the cluster past ``max_nodes`` re-buckets to the next
power of two (one recompile), mirroring how the reference grows by adding
scheduler shards (reference README.adoc:697-712) — except here a "shard" is
a slice of one HBM-resident tensor.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class TableSpec:
    """Shape of the device-resident node table.

    The reference keeps ~100KB/node in Go informer caches
    (reference RUNNING.adoc:193); this table costs a few hundred bytes/node,
    so 1M nodes fit comfortably in a single chip's HBM.
    """

    max_nodes: int = 1 << 20
    label_slots: int = 16      # padded label (key,value) pairs per node
    taint_slots: int = 8       # padded taints per node
    max_taint_ids: int = 128   # distinct (key,value,effect) taint triples cluster-wide
    max_zones: int = 512       # distinct topology.kubernetes.io/zone values
    max_regions: int = 64
    # Active topology-spread / inter-pod-affinity constraint slots.  Slots
    # are interned host-side and recycled; only constraints referenced by
    # in-flight pods need to be resident.
    spread_slots: int = 16
    affinity_slots: int = 16

    def __post_init__(self):
        if self.max_nodes <= 0:
            raise ValueError("max_nodes must be positive")


@dataclasses.dataclass(frozen=True)
class PodSpec:
    """Shape of one encoded pod batch."""

    batch: int = 256
    query_keys: int = 16       # distinct label keys referenced by one batch's selectors
    aff_terms: int = 4         # required nodeAffinity terms (OR of terms)
    aff_exprs: int = 4         # expressions per term (ANDed)
    aff_values: int = 8        # values per expression (In/NotIn sets)
    pref_terms: int = 4        # preferred nodeAffinity terms
    spread_refs: int = 4       # topologySpreadConstraints per pod
    affinity_refs: int = 4     # (anti)affinity terms per pod
    spread_incs: int = 4       # spread constraints whose selector matches the pod
    ipa_incs: int = 4          # affinity terms whose selector matches the pod


# Sentinel id meaning "no string" in every interned column.  Real ids start
# at 1 so zero-initialised padding is automatically "absent".
NONE_ID = 0

# The schedulerName this framework answers to (the reference's intake
# filter, webhook.go:102-125).  Single source of truth — the coordinator
# ignores every pod whose schedulerName differs, so a drifted copy would
# silently schedule nothing.
DEFAULT_SCHEDULER = "dist-scheduler"
# What Kubernetes assigns when spec.schedulerName is unset; such pods
# belong to the stock scheduler, never to this framework's intake.
K8S_DEFAULT_SCHEDULER = "default-scheduler"

# Taint / toleration effects (reference mem of upstream v1.Taint effects).
EFFECT_NONE = 0                # toleration with no effect: matches all
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3

# Toleration operators.
TOL_OP_EQUAL = 0
TOL_OP_EXISTS = 1

# NodeSelector operators (upstream v1.NodeSelectorOperator).
SEL_OP_IN = 0
SEL_OP_NOT_IN = 1
SEL_OP_EXISTS = 2
SEL_OP_DOES_NOT_EXIST = 3
SEL_OP_GT = 4
SEL_OP_LT = 5

# Topology keys get dedicated dense columns (domain-count tables need dense
# domain ids; generic labels stay in the hashed slots).
TOPO_HOSTNAME = 0              # kubernetes.io/hostname — domain == node
TOPO_ZONE = 1                  # topology.kubernetes.io/zone
TOPO_REGION = 2                # topology.kubernetes.io/region

# whenUnsatisfiable modes for topology spread.
SPREAD_DO_NOT_SCHEDULE = 0
SPREAD_SCHEDULE_ANYWAY = 1

# Numeric value parsed out of a label for Gt/Lt node-affinity operators;
# this sentinel means "not an integer".
NO_NUMERIC = -(1 << 31)
