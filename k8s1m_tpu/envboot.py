"""Clean-environment bootstrap shared by the test suite and driver entry.

This environment force-registers the axon TPU backend from a sitecustomize
hook on PYTHONPATH at interpreter start.  A process that has initialised
(or will initialise) that backend cannot host a virtual multi-device CPU
mesh, so both pytest (tests/conftest.py) and the driver's multi-chip dry
run (__graft_entry__.dryrun_multichip) re-launch themselves in a child
interpreter built from :func:`cleaned_cpu_env`.

Must stay importable without jax (it runs before backend selection).
"""

from __future__ import annotations

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def cleaned_cpu_env(environ, n_devices: int) -> dict:
    """A copy of ``environ`` prepared for an ``n_devices`` CPU-mesh child:
    axon stripped from PYTHONPATH, JAX_PLATFORMS=cpu, and the virtual
    device count forced (replacing any existing count flag)."""
    env = dict(environ)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if p and "axon_site" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split() if not f.startswith(_COUNT_FLAG)
    ]
    flags.append(f"{_COUNT_FLAG}={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
