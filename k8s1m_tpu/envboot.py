"""Clean-environment bootstrap shared by the test suite and driver entry.

This environment force-registers the axon TPU backend from a sitecustomize
hook on PYTHONPATH at interpreter start.  A process that has initialised
(or will initialise) that backend cannot host a virtual multi-device CPU
mesh, so both pytest (tests/conftest.py) and the driver's multi-chip dry
run (__graft_entry__.dryrun_multichip) re-launch themselves in a child
interpreter built from :func:`cleaned_cpu_env`.

Must stay importable without jax (it runs before backend selection).
"""

from __future__ import annotations

import gc

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def tune_gc(gen0: int = 50_000, gen1: int = 50, gen2: int = 50) -> None:
    """Relax the cyclic-GC cadence for a serving hot loop.

    The reference deploys its Go scheduler fleet with GOGC≈700-1000 and a
    GOMEMLIMIT because collector pressure was a measured tail-latency and
    throughput cost at 14K pods/s (reference README.adoc:672-677,
    terraform/kubernetes/dist-scheduler.tf:220-228).  The CPython analogue:
    the coordinator's intake loop allocates hundreds of thousands of
    small, acyclic objects per second (event tuples, byte slices,
    PendingPods) while holding large long-lived dicts (_bound), so the
    default gen0 threshold of 700 fires the collector thousands of times
    a second and every gen2 pass rescans the bound-pod table — measured
    at ~35% of end-to-end schedule-to-bind throughput on one core.
    Refcounting reclaims the acyclic garbage either way; raising the
    thresholds keeps cycle collection for what actually needs it.

    Objects that survived startup never become garbage in steady state:
    freeze them out of the young generations entirely.
    """
    gc.collect()
    gc.freeze()
    gc.set_threshold(gen0, gen1, gen2)


def cleaned_cpu_env(environ, n_devices: int) -> dict:
    """A copy of ``environ`` prepared for an ``n_devices`` CPU-mesh child:
    axon stripped from PYTHONPATH, JAX_PLATFORMS=cpu, and the virtual
    device count forced (replacing any existing count flag)."""
    env = dict(environ)
    env["PYTHONPATH"] = ":".join(
        p for p in env.get("PYTHONPATH", "").split(":") if p and "axon_site" not in p
    )
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split() if not f.startswith(_COUNT_FLAG)
    ]
    flags.append(f"{_COUNT_FLAG}={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env
