"""Async etcd v3 client over the hand-authored proto subset.

Used by the load generators (tools/) and tests; the same role the
reference's stress-client and etcd clientv3 users play
(reference mem_etcd/stress-client/src/main.rs, etcd-lease-flood/main.go).
Works against any etcd v3 server, not just ours — the wire format is the
public one.
"""

from __future__ import annotations

import asyncio
import dataclasses

import grpc
from grpc import aio

from k8s1m_tpu.store.native import pack_bind_frame, pack_put_frame, prefix_end
from k8s1m_tpu.store.proto import batch_pb2, rpc_pb2

_M = "etcdserverpb"


@dataclasses.dataclass
class WatchBatch:
    events: list          # list[mvcc_pb2.Event]
    revision: int         # header revision of the response
    compact_revision: int = 0
    created: bool = False
    canceled: bool = False
    cancel_reason: str = ""


def secure_channel_for(
    target: str,
    ca_pem: str,
    token: str | None = None,
    options: list[tuple[str, int | str]] | None = None,
    _aio: bool = True,
):
    """A TLS channel trusting only ``ca_pem`` (the rig CA,
    cluster/certs.py), optionally attaching ``authorization: Bearer
    <token>`` call credentials — the client half of the tier's
    apiserver-style TLS + bearer auth."""
    with open(ca_pem, "rb") as f:
        creds = grpc.ssl_channel_credentials(root_certificates=f.read())
    if token is not None:
        creds = grpc.composite_channel_credentials(
            creds, grpc.access_token_call_credentials(token)
        )
    mk = aio.secure_channel if _aio else grpc.secure_channel
    return mk(target, creds, options=options)


class EtcdClient:
    def __init__(
        self,
        target: str,
        channel: aio.Channel | None = None,
        options: list[tuple[str, int | str]] | None = None,
        *,
        ca_pem: str | None = None,
        token: str | None = None,
    ):
        if channel is None and ca_pem is not None:
            channel = secure_channel_for(
                target, ca_pem, token, options=options
            )
        self.channel = channel or aio.insecure_channel(target, options=options)
        c = self.channel
        pb = rpc_pb2

        def u(svc, name, req, resp):
            return c.unary_unary(
                f"/{_M}.{svc}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )

        self._range = u("KV", "Range", pb.RangeRequest, pb.RangeResponse)
        self._put = u("KV", "Put", pb.PutRequest, pb.PutResponse)
        self._delete = u("KV", "DeleteRange", pb.DeleteRangeRequest, pb.DeleteRangeResponse)
        self._txn = u("KV", "Txn", pb.TxnRequest, pb.TxnResponse)
        self._compact = u("KV", "Compact", pb.CompactionRequest, pb.CompactionResponse)
        self._lease_grant = u("Lease", "LeaseGrant", pb.LeaseGrantRequest, pb.LeaseGrantResponse)
        self._lease_revoke = u("Lease", "LeaseRevoke", pb.LeaseRevokeRequest, pb.LeaseRevokeResponse)
        self._status = u("Maintenance", "Status", pb.StatusRequest, pb.StatusResponse)
        self._watch_stream = c.stream_stream(
            f"/{_M}.Watch/Watch",
            request_serializer=pb.WatchRequest.SerializeToString,
            response_deserializer=pb.WatchResponse.FromString,
        )
        self._put_frame = c.unary_unary(
            "/k8s1m.BatchKV/PutFrame",
            request_serializer=batch_pb2.PutFrameRequest.SerializeToString,
            response_deserializer=batch_pb2.PutFrameResponse.FromString,
        )
        self._bind_frame = c.unary_unary(
            "/k8s1m.BatchKV/BindFrame",
            request_serializer=batch_pb2.BindFrameRequest.SerializeToString,
            response_deserializer=batch_pb2.BindFrameResponse.FromString,
        )

    async def close(self):
        await self.channel.close()

    # ---- KV ------------------------------------------------------------

    async def put(self, key: bytes, value: bytes, lease: int = 0) -> int:
        resp = await self._put(rpc_pb2.PutRequest(key=key, value=value, lease=lease))
        return resp.header.revision

    async def get(self, key: bytes):
        resp = await self._range(rpc_pb2.RangeRequest(key=key))
        return resp.kvs[0] if resp.kvs else None

    async def range(
        self,
        key: bytes,
        range_end: bytes = b"",
        *,
        limit: int = 0,
        revision: int = 0,
        count_only: bool = False,
        keys_only: bool = False,
    ) -> rpc_pb2.RangeResponse:
        return await self._range(
            rpc_pb2.RangeRequest(
                key=key,
                range_end=range_end,
                limit=limit,
                revision=revision,
                count_only=count_only,
                keys_only=keys_only,
            )
        )

    async def prefix(self, prefix: bytes, **kwargs) -> rpc_pb2.RangeResponse:
        return await self.range(prefix, prefix_end(prefix), **kwargs)

    async def delete(self, key: bytes, range_end: bytes = b"") -> int:
        resp = await self._delete(
            rpc_pb2.DeleteRangeRequest(key=key, range_end=range_end)
        )
        return resp.deleted

    async def txn_cas(
        self,
        key: bytes,
        value: bytes | None,
        *,
        required_mod: int | None = None,
        required_version: int | None = None,
        lease: int = 0,
        want_current_on_failure: bool = True,
    ) -> rpc_pb2.TxnResponse:
        """The Kubernetes Txn shape: compare mod/version, put-or-delete."""
        if (required_mod is None) == (required_version is None):
            raise ValueError("exactly one of required_mod/required_version")
        if required_mod is not None:
            cmp = rpc_pb2.Compare(
                result=rpc_pb2.Compare.EQUAL,
                target=rpc_pb2.Compare.MOD,
                key=key,
                mod_revision=required_mod,
            )
        else:
            cmp = rpc_pb2.Compare(
                result=rpc_pb2.Compare.EQUAL,
                target=rpc_pb2.Compare.VERSION,
                key=key,
                version=required_version,
            )
        op = rpc_pb2.RequestOp()
        if value is None:
            op.request_delete_range.key = key
        else:
            op.request_put.key = key
            op.request_put.value = value
            op.request_put.lease = lease
        req = rpc_pb2.TxnRequest(compare=[cmp], success=[op])
        if want_current_on_failure:
            fail = rpc_pb2.RequestOp()
            fail.request_range.key = key
            req.failure.append(fail)
        return await self._txn(req)

    async def put_batch(
        self, items: list[tuple[bytes, bytes | None]], lease: int = 0
    ) -> int:
        """Pipelined write wave over the private BatchKV extension (our
        server only — not part of the public etcd surface).  value None =
        delete.  Returns the store revision after the wave."""
        resp = await self._put_frame(
            batch_pb2.PutFrameRequest(
                frame=pack_put_frame(items), count=len(items), lease=lease
            )
        )
        return resp.revision

    async def bind_batch(
        self, binds: list[tuple[bytes, int, bytes]]
    ) -> list[int]:
        """Bind wave (key, required_mod, node_name) -> per-record revision
        or -1 (CAS conflict) / -5 (not spliceable).  BatchKV extension."""
        resp = await self._bind_frame(
            batch_pb2.BindFrameRequest(
                frame=pack_bind_frame(binds), count=len(binds)
            )
        )
        return list(resp.revisions)

    async def compact(self, revision: int) -> None:
        await self._compact(rpc_pb2.CompactionRequest(revision=revision))

    # ---- Lease / Maintenance ------------------------------------------

    async def lease_grant(self, ttl: int) -> int:
        resp = await self._lease_grant(rpc_pb2.LeaseGrantRequest(TTL=ttl))
        return resp.ID

    async def lease_revoke(self, lease_id: int) -> None:
        await self._lease_revoke(rpc_pb2.LeaseRevokeRequest(ID=lease_id))

    async def status(self) -> rpc_pb2.StatusResponse:
        return await self._status(rpc_pb2.StatusRequest())

    # ---- Watch ---------------------------------------------------------

    def watch(
        self,
        key: bytes,
        range_end: bytes = b"",
        *,
        start_revision: int = 0,
        prev_kv: bool = False,
    ) -> "WatchSession":
        return WatchSession(self, key, range_end, start_revision, prev_kv)


class WatchSession:
    """One watch on its own bidi stream; iterate for WatchBatch objects."""

    def __init__(self, client: EtcdClient, key, range_end, start_revision, prev_kv):
        self._client = client
        self._req = rpc_pb2.WatchRequest(
            create_request=rpc_pb2.WatchCreateRequest(
                key=key,
                range_end=range_end,
                start_revision=start_revision,
                prev_kv=prev_kv,
            )
        )
        self._call = None
        self._read_task: asyncio.Task | None = None
        self.watch_id = None
        self.compact_revision = 0

    async def __aenter__(self):
        self._call = self._client._watch_stream()
        await self._call.write(self._req)
        first = await self._call.read()
        self.watch_id = first.watch_id
        self.compact_revision = first.compact_revision
        self.canceled = first.canceled
        return self

    async def __aexit__(self, *exc):
        await self.cancel()

    async def cancel(self):
        if self._call is not None:
            try:
                await self._call.write(
                    rpc_pb2.WatchRequest(
                        cancel_request=rpc_pb2.WatchCancelRequest(
                            watch_id=self.watch_id or 0
                        )
                    )
                )
                await self._call.done_writing()
            # Half-close on teardown is best-effort; cancel() below is
            # the authoritative cleanup.
            except Exception:  # graftlint: disable=broad-except
                pass
            if self._read_task is not None:
                self._read_task.cancel()
                self._read_task = None
            self._call.cancel()
            self._call = None

    def _live_call(self):
        if self._call is None:
            raise RuntimeError("watch session is closed")
        return self._call

    async def request_progress(self) -> None:
        await self._live_call().write(
            rpc_pb2.WatchRequest(progress_request=rpc_pb2.WatchProgressRequest())
        )

    async def next(self, timeout: float | None = None) -> WatchBatch:
        # A timed-out wait must not cancel the underlying stream read:
        # grpc.aio cancels the WHOLE call when its read future is
        # cancelled, so wait_for's timeout used to kill the session the
        # first time a quiet watch hit it.  Park the read on a task,
        # shield it, and resume the SAME read on the next call — a read
        # that completed between calls still hands over its batch (the
        # await below returns a done task's buffered result instantly).
        if self._read_task is None:
            call = self._live_call()
            self._read_task = asyncio.ensure_future(call.read())
        resp = await asyncio.wait_for(
            asyncio.shield(self._read_task), timeout
        )
        self._read_task = None
        return WatchBatch(
            events=list(resp.events),
            revision=resp.header.revision,
            compact_revision=resp.compact_revision,
            created=resp.created,
            canceled=resp.canceled,
            cancel_reason=resp.cancel_reason,
        )
