"""ctypes bindings for the native memstore (the mem_etcd equivalent).

One MemStore == one in-process store instance; the etcd gRPC wire layer
(k8s1m_tpu/store/etcd_server.py) serves it over the network with the same
API subset the reference implements (reference mem_etcd/src/kv_service.rs,
watch_service.rs).  Binary result layouts are defined in
native/memstore/memstore.h.
"""

from __future__ import annotations

import ctypes
import dataclasses
import struct

from k8s1m_tpu.obs.metrics import Counter
from k8s1m_tpu.store.build import ensure_built

_RELIST_COMPACTED = Counter(
    "memstore_relist_compacted_retries_total",
    "pinned relist restarts after the snapshot revision fell out of the "
    "compaction window mid-scan (the reflector-on-410 rule)", ()
)

WAL_NONE = 0
WAL_BUFFERED = 1
WAL_FSYNC = 2
_WAL_MODES = {"none": WAL_NONE, "buffered": WAL_BUFFERED, "fsync": WAL_FSYNC}

_ERR_CAS = -1
_ERR_COMPACTED = -2
_ERR_FUTURE_REV = -3
_ERR_NOT_FOUND = -4
# Public: bind_batch result for "object not spliceable, use the slow path".
BIND_INVALID = -5

# etcd convention: range end of a single zero byte means "to infinity".
INFINITY = b"\x00"


class CompactedError(Exception):
    def __init__(self, compact_revision: int = 0):
        super().__init__(f"revision compacted (compact_revision={compact_revision})")
        self.compact_revision = compact_revision


class FutureRevError(Exception):
    pass


def prefix_end(prefix: bytes) -> bytes:
    """etcd's prefix-range end: prefix with its last byte incremented
    (the /a/b/c/ -> /a/b/c0 idiom, reference store.rs:536-588)."""
    p = bytearray(prefix)
    for i in reversed(range(len(p))):
        if p[i] < 0xFF:
            p[i] += 1
            return bytes(p[: i + 1])
    return INFINITY


@dataclasses.dataclass(frozen=True)
class KeyValue:
    key: bytes
    value: bytes
    create_revision: int
    mod_revision: int
    version: int
    lease: int = 0


@dataclasses.dataclass(frozen=True)
class RangeResult:
    revision: int       # store revision at read time
    # Total matches when limit=0 (or count_only); with limit>0 the scan
    # stops one element past the limit, so count is approximate (at most
    # limit+1 — proof of `more`, not a total).  etcd permits this and
    # Kubernetes tolerates it (reference README.adoc:326-328).
    count: int
    more: bool
    kvs: list[KeyValue]


@dataclasses.dataclass(frozen=True)
class WatchEvent:
    type: str           # "PUT" | "DELETE"
    kv: KeyValue
    prev_kv: KeyValue | None = None


# ms_watch_poll_pods flag bits (memstore.h MS_POD_*).
POD_CANONICAL = 1
POD_HAS_NODE = 2
POD_SCHED_MATCH = 4


@dataclasses.dataclass
class PodEventBatch:
    """Columnar view of one ms_watch_poll_pods drain (zero-copy numpy
    views into the single result buffer; layout in memstore.h)."""

    n: int
    canceled: bool
    etype: "object"     # u8[n]   0 PUT, 1 DELETE
    flags: "object"     # u8[n]   POD_* bits
    mrev: "object"      # i64[n]
    cpu: "object"       # i32[n]
    mem: "object"       # i32[n]
    koff: "object"      # u32[n+1] offsets into key_blob
    aoff: "object"      # u32[n+1] offsets into aux_blob
    key_blob: bytes
    aux_blob: bytes

    @staticmethod
    def empty() -> "PodEventBatch":
        import numpy as np

        z = np.zeros(0, np.uint8)
        o = np.zeros(1, np.uint32)
        return PodEventBatch(
            0, False, z, z, np.zeros(0, np.int64), np.zeros(0, np.int32),
            np.zeros(0, np.int32), o, o, b"", b"",
        )

    @staticmethod
    def parse(data: bytes) -> "PodEventBatch":
        import numpy as np

        (n,) = _U32.unpack_from(data, 0)
        canceled = bool(data[4])
        off = 8
        etype = np.frombuffer(data, np.uint8, n, off); off += n
        flags = np.frombuffer(data, np.uint8, n, off); off += n
        off += (8 - off % 8) % 8
        mrev = np.frombuffer(data, np.int64, n, off); off += 8 * n
        cpu = np.frombuffer(data, np.int32, n, off); off += 4 * n
        mem = np.frombuffer(data, np.int32, n, off); off += 4 * n
        koff = np.frombuffer(data, np.uint32, n + 1, off); off += 4 * (n + 1)
        aoff = np.frombuffer(data, np.uint32, n + 1, off); off += 4 * (n + 1)
        klen = int(koff[-1])
        key_blob = data[off : off + klen]; off += klen
        aux_blob = data[off : off + int(aoff[-1])]
        return PodEventBatch(
            int(n), canceled, etype, flags, mrev, cpu, mem, koff, aoff,
            key_blob, aux_blob,
        )


_KV_FIXED = struct.Struct("<IIqqqq")  # klen, vlen, create, mod, version, lease
_U32 = struct.Struct("<I")
_U32X2 = struct.Struct("<II")
_PUT_REC = struct.Struct("<II")       # klen, vlen (0xFFFFFFFF = delete)
_BIND_REC = struct.Struct("<qII")     # required_mod, klen, nlen
_DELETE_MARKER = 0xFFFFFFFF


def pack_put_frame(items: list[tuple[bytes, bytes | None]]) -> bytes:
    """Pack puts/deletes (value None = delete) into the ms_put_batch frame
    format — also the wire form of BatchKV.PutFrame (proto/batch.proto)."""
    parts = []
    pack = _PUT_REC.pack
    for key, value in items:
        if value is None:
            parts.append(pack(len(key), _DELETE_MARKER))
            parts.append(key)
        else:
            parts.append(pack(len(key), len(value)))
            parts.append(key)
            parts.append(value)
    return b"".join(parts)


def pack_bind_frame(binds: list[tuple[bytes, int, bytes]]) -> bytes:
    """Pack (key, required_mod, node_name) bind records into the
    ms_bind_batch frame format — also the wire form of BatchKV.BindFrame."""
    parts = []
    pack = _BIND_REC.pack
    for key, required_mod, name in binds:
        parts.append(pack(required_mod, len(key), len(name)))
        parts.append(key)
        parts.append(name)
    return b"".join(parts)


def _parse_kv(buf: memoryview, off: int) -> tuple[KeyValue, int]:
    klen, vlen, crev, mrev, ver, lease = _KV_FIXED.unpack_from(buf, off)
    off += _KV_FIXED.size
    key = bytes(buf[off : off + klen]); off += klen
    val = bytes(buf[off : off + vlen]); off += vlen
    return KeyValue(key, val, crev, mrev, ver, lease), off


def read_varint(buf: bytes, off: int) -> tuple[int, int]:
    """(value, next_offset) of the protobuf varint at ``off``."""
    val = 0
    shift = 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


def decode_shared_tail(data: bytes) -> tuple[list[int], int, int]:
    """Decode the wiretier shared-frame extension of one serialized
    WatchResponse (store/wiretier.py): trailing private fields 100
    (repeated varint — the EXTRA watch ids sharing this frame's bytes)
    and 101 (varint — a compacted frame's window lower bound).

    Returns ``(extra_wids, from_rev, core_len)`` where ``core_len`` is
    the byte length of the frame up to the first extension field — i.e.
    the exact unshared single-watch response the primary id would have
    received, the quantity the storm drill's bytes accounting compares
    against.  A frame without the extension returns
    ``([], 0, len(data))``, so callers can run this unconditionally.

    This is a top-level field scan, not a parse: a WatchResponse is a
    handful of top-level fields however many events it carries, and
    protobuf framing lets every non-matching field be skipped by
    length.  No protobuf dependency — this is the wire client's side of
    the contract, next to the store's other frame codecs.
    """
    wids: list[int] = []
    from_rev = 0
    core = len(data)
    off = 0
    n = len(data)
    while off < n:
        at = off
        key, off = read_varint(data, off)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, off = read_varint(data, off)
        elif wt == 2:
            ln, off = read_varint(data, off)
            off += ln
            val = 0
        elif wt == 5:
            off += 4
            val = 0
        elif wt == 1:
            off += 8
            val = 0
        else:
            break   # start/end-group or junk: nothing of ours follows
        if wt == 0 and field == 100:
            wids.append(val)
            if at < core:
                core = at
        elif wt == 0 and field == 101:
            from_rev = val
            if at < core:
                core = at
    return wids, from_rev, core


def _load_lib():
    lib = ctypes.CDLL(ensure_built())
    c = ctypes
    P8 = c.POINTER(c.c_uint8)
    lib.ms_open.restype = c.c_void_p
    lib.ms_open.argtypes = [c.c_char_p, c.c_int, c.c_char_p]
    lib.ms_close.argtypes = [c.c_void_p]
    lib.ms_free.argtypes = [c.c_void_p]
    lib.ms_set.restype = c.c_int64
    lib.ms_set.argtypes = [
        c.c_void_p, c.c_char_p, c.c_size_t, c.c_char_p, c.c_size_t,
        c.c_int, c.c_int, c.c_int64, c.c_int64,
        c.POINTER(c.c_int64), c.POINTER(P8), c.POINTER(c.c_size_t),
    ]
    lib.ms_range.restype = c.c_int
    lib.ms_range.argtypes = [
        c.c_void_p, c.c_char_p, c.c_size_t, c.c_char_p, c.c_size_t,
        c.c_int64, c.c_int64, c.c_int, c.c_int,
        c.POINTER(P8), c.POINTER(c.c_size_t),
    ]
    for name in ("ms_current_revision", "ms_compact_revision",
                 "ms_progress_revision", "ms_num_keys", "ms_db_size"):
        fn = getattr(lib, name)
        fn.restype = c.c_int64
        fn.argtypes = [c.c_void_p]
    lib.ms_compact.restype = c.c_int
    lib.ms_compact.argtypes = [c.c_void_p, c.c_int64]
    lib.ms_watch_create.restype = c.c_int64
    lib.ms_watch_create.argtypes = [
        c.c_void_p, c.c_char_p, c.c_size_t, c.c_char_p, c.c_size_t,
        c.c_int64, c.c_int, c.c_int64, c.POINTER(c.c_int64),
    ]
    lib.ms_watch_cancel.restype = c.c_int
    lib.ms_watch_cancel.argtypes = [c.c_void_p, c.c_int64]
    lib.ms_watch_poll.restype = c.c_int
    lib.ms_watch_poll.argtypes = [
        c.c_void_p, c.c_int64, c.c_int, c.c_int,
        c.POINTER(P8), c.POINTER(c.c_size_t),
    ]
    lib.ms_watch_dropped.restype = c.c_int64
    lib.ms_watch_dropped.argtypes = [c.c_void_p, c.c_int64]
    lib.ms_watch_pending.restype = c.c_int64
    lib.ms_watch_pending.argtypes = [c.c_void_p, c.c_int64]
    lib.ms_stats_json.restype = c.c_int
    lib.ms_stats_json.argtypes = [c.c_void_p, c.POINTER(P8), c.POINTER(c.c_size_t)]
    lib.ms_put_batch.restype = c.c_int64
    lib.ms_put_batch.argtypes = [
        c.c_void_p, c.c_char_p, c.c_size_t, c.c_int, c.c_int64,
    ]
    lib.ms_bind_batch.restype = c.c_int
    lib.ms_bind_batch.argtypes = [
        c.c_void_p, c.c_char_p, c.c_size_t, c.c_int, c.c_int64,
        c.POINTER(c.POINTER(c.c_int64)),
    ]
    lib.ms_watch_poll_pods.restype = c.c_int
    lib.ms_watch_poll_pods.argtypes = [
        c.c_void_p, c.c_int64, c.c_int, c.c_char_p, c.c_size_t,
        c.POINTER(P8), c.POINTER(c.c_size_t),
    ]
    lib.ms_parse_pod_events.restype = c.c_int
    lib.ms_parse_pod_events.argtypes = [
        c.c_char_p, c.c_size_t, c.c_int, c.c_char_p, c.c_size_t,
        c.POINTER(P8), c.POINTER(c.c_size_t),
    ]
    lib.ms_wal_sync.restype = c.c_int
    lib.ms_wal_sync.argtypes = [c.c_void_p]
    lib.wf_start.restype = c.c_void_p
    lib.wf_start.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int]
    lib.wf_port.restype = c.c_int
    lib.wf_port.argtypes = [c.c_void_p]
    lib.wf_stop.argtypes = [c.c_void_p]
    lib.wf_stress_put.restype = c.c_int64
    lib.wf_stress_put.argtypes = [
        c.c_char_p, c.c_int, c.c_int64, c.c_int, c.c_char_p, c.c_int64,
        c.c_int, c.POINTER(c.c_double),
    ]
    return lib


_LIB = None


def _lib():
    global _LIB
    if _LIB is None:
        _LIB = _load_lib()
    return _LIB


def _take_buf(lib, pp, plen) -> bytes:
    if not pp:
        return b""
    data = ctypes.string_at(pp, plen.value)
    lib.ms_free(pp)
    return data


class Watcher:
    """Handle to one store watcher; poll() returns revision-ordered events."""

    def __init__(self, store: "MemStore", wid: int):
        self._store = store
        self.id = wid
        self.canceled = False

    def poll(self, max_events: int = 1000, timeout_ms: int = 0) -> list[WatchEvent]:
        lib = _lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        n = lib.ms_watch_poll(
            self._store._h, self.id, max_events, timeout_ms,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if n == _ERR_NOT_FOUND:
            self.canceled = True
            return []
        data = _take_buf(lib, out, out_len)
        buf = memoryview(data)
        (n_events,) = struct.unpack_from("<I", buf, 0)
        if buf[4]:
            self.canceled = True
        off = 5
        events = []
        for _ in range(n_events):
            etype, has_prev = buf[off], buf[off + 1]
            off += 2
            kv, off = _parse_kv(buf, off)
            prev = None
            if has_prev:
                prev, off = _parse_kv(buf, off)
            events.append(
                WatchEvent("DELETE" if etype else "PUT", kv, prev)
            )
        return events

    def poll_light(
        self, max_events: int = 1000, timeout_ms: int = 0
    ) -> list[tuple[int, bytes, bytes, int]]:
        """Like poll(), but returns ``(type, key, value, mod_revision)``
        tuples (type 0=PUT, 1=DELETE) and skips prev-kv parsing — the
        coordinator's firehose path, where per-event dataclass
        construction is measurable at 100K events/s."""
        lib = _lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        n = lib.ms_watch_poll(
            self._store._h, self.id, max_events, timeout_ms,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if n == _ERR_NOT_FOUND:
            self.canceled = True
            return []
        data = _take_buf(lib, out, out_len)
        if data[4]:
            self.canceled = True
        (n_events,) = _U32.unpack_from(data, 0)
        off = 5
        events = []
        unpack = _KV_FIXED.unpack_from
        size = _KV_FIXED.size
        for _ in range(n_events):
            etype, has_prev = data[off], data[off + 1]
            off += 2
            klen, vlen, _crev, mrev, _ver, _lease = unpack(data, off)
            off += size
            key = data[off : off + klen]; off += klen
            val = data[off : off + vlen]; off += vlen
            if has_prev:
                pklen, pvlen = _U32X2.unpack_from(data, off)
                off += size + pklen + pvlen
            events.append((etype, key, val, mrev))
        return events

    def poll_pods(
        self, max_events: int = 10000, scheduler_name: bytes = b""
    ) -> "PodEventBatch":
        """Native drain + canonical-pod parse (ms_watch_poll_pods): the
        coordinator's intake firehose comes back as columnar numpy arrays
        instead of per-event Python objects — ~6x less host time per
        event than poll_light + decode_pod_fast."""
        lib = _lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = lib.ms_watch_poll_pods(
            self._store._h, self.id, max_events,
            scheduler_name, len(scheduler_name),
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if rc == _ERR_NOT_FOUND:
            self.canceled = True
            return PodEventBatch.empty()
        data = _take_buf(lib, out, out_len)
        evb = PodEventBatch.parse(data)
        if evb.canceled:
            self.canceled = True
        return evb

    @property
    def dropped(self) -> int:
        return _lib().ms_watch_dropped(self._store._h, self.id)

    @property
    def pending(self) -> int:
        """Queued-event count, without consuming anything."""
        return max(0, _lib().ms_watch_pending(self._store._h, self.id))

    def cancel(self) -> None:
        if not self.canceled:
            _lib().ms_watch_cancel(self._store._h, self.id)
            self.canceled = True


_POD_EV_REC = struct.Struct("<bqII")


def parse_pod_events(
    events, scheduler_name: bytes = b""
) -> PodEventBatch:
    """Run the native canonical-pod parser over already-received events
    (``(etype, key, value, mod_revision)`` tuples, e.g. a RemoteWatcher's
    buffered wire events) — the store-independent half of poll_pods, so
    the wire topology gets the same columnar fast lane as the in-process
    store."""
    lib = _lib()
    parts = []
    pack = _POD_EV_REC.pack
    n = 0
    for etype, key, value, mrev in events:
        v = value or b""
        parts.append(pack(etype, mrev, len(key), len(v)))
        parts.append(key)
        parts.append(v)
        n += 1
    frame = b"".join(parts)
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    rc = lib.ms_parse_pod_events(
        frame, len(frame), n, scheduler_name, len(scheduler_name),
        ctypes.byref(out), ctypes.byref(out_len),
    )
    if rc < 0:
        raise ValueError(f"ms_parse_pod_events rc={rc}")
    return PodEventBatch.parse(_take_buf(lib, out, out_len))


def list_prefix(
    store, prefix: bytes, *, page: int = 5000, keys_only: bool = False,
    revision: int = 0,
):
    """Consistent paginated list of a prefix: (kvs, revision).

    The first page pins the snapshot revision and every later page reads
    at it (etcd's paginated-list contract; kube reflectors depend on it
    for the list+watch handoff).  Unpaginated lists break the WIRE
    topology outright: a gRPC response carrying 1M nodes is ~350MB,
    far over any sane message cap — the reference's controllers never
    list unpaginated either (client-go chunks at 500).
    Restarts the scan from the current revision if the pinned revision
    is compacted mid-scan (the reflector-on-410-Gone rule), up to 3
    attempts.

    ``revision`` > 0 pins the whole list at a CALLER-CHOSEN revision —
    the follow-mode relist a promoting warm standby uses to diff its
    mirror against the store as of the lease-acquire revision
    (control/coordinator.Coordinator._reconcile_at).  A pinned list that
    hits compaction raises instead of restarting (silently listing a
    different revision would defeat the diff); the caller owns the
    fallback.
    """
    for _ in range(3):
        start, end = prefix, prefix_end(prefix)
        out: list = []
        rev = revision
        try:
            while True:
                res = store.range(
                    start, end, limit=page, keys_only=keys_only, revision=rev
                )
                if rev == 0:
                    rev = res.revision
                out.extend(res.kvs)
                if not res.more or not res.kvs:
                    return out, rev
                start = res.kvs[-1].key + b"\x00"
        except CompactedError:
            if revision:
                raise
            _RELIST_COMPACTED.inc()
            continue
    raise CompactedError()


def list_prefix_values(store, prefix: bytes, *, page: int = 5000):
    """Values-only ``list_prefix``: ``(values, revision)`` with the
    same pinned-snapshot pagination contract, skipping per-KV object
    construction entirely (``MemStore.range_values``).  The megarow
    cold relist reads a million stored Nodes whose names live in the
    objects — building a million KeyValue dataclasses plus key bytes
    just to drop them was a measured slice of the cold-build wall.
    Falls back to ``list_prefix`` for stores without the light parse
    (remote wire clients)."""
    rv = getattr(store, "range_values", None)
    if rv is None:
        kvs, rev = list_prefix(store, prefix, page=page)
        return [kv.value for kv in kvs], rev
    for _ in range(3):
        start, end = prefix, prefix_end(prefix)
        out: list = []
        rev = 0
        try:
            while True:
                r, more, vals, last = rv(
                    start, end, limit=page, revision=rev
                )
                if rev == 0:
                    rev = r
                out.extend(vals)
                if not more or not vals:
                    return out, rev
                start = last + b"\x00"
        except CompactedError:
            _RELIST_COMPACTED.inc()
            continue
    raise CompactedError()


def list_prefix_sharded(
    store, prefix: bytes, *, shards: int = 8, page: int = 5000,
):
    """``list_prefix`` with the value fetch fanned out over key-range
    shards: one keys-only paginated pass pins the snapshot revision and
    yields shard boundaries, then ``shards`` concurrent range scans pull
    the values at that revision.  Returns ``(kvs, revision)`` with kvs
    in key order — byte-identical to ``list_prefix`` (tier-1 gate).

    This is the megarow cold-relist shape for WIRE stores, where the
    per-page round trip and proto decode overlap across shards.  For
    the in-process MemStore the parse is GIL-bound and sharding buys
    nothing — pass ``shards=1`` (or call ``list_prefix``) there; the
    coordinator picks per store type (control/coordinator._relist).
    """
    if shards <= 1:
        return list_prefix(store, prefix, page=page)
    from concurrent.futures import ThreadPoolExecutor

    for _ in range(3):
        keys, rev = list_prefix(store, prefix, page=page, keys_only=True)
        n = len(keys)
        if n == 0:
            return [], rev
        nshards = min(shards, n)
        bounds = [keys[i * n // nshards].key for i in range(nshards)]
        bounds.append(prefix_end(prefix))

        def fetch(i: int) -> list:
            out: list = []
            start, end = bounds[i], bounds[i + 1]
            while True:
                res = store.range(start, end, limit=page, revision=rev)
                out.extend(res.kvs)
                if not res.more or not res.kvs:
                    return out
                start = res.kvs[-1].key + b"\x00"

        try:
            with ThreadPoolExecutor(nshards) as ex:
                parts = list(ex.map(fetch, range(nshards)))
        except CompactedError:
            # The pin fell out of the store's window mid-fetch (heavy
            # write load + aggressive compaction): re-pin and restart,
            # the same reflector-on-410 rule as list_prefix.
            _RELIST_COMPACTED.inc()
            continue
        return [kv for part in parts for kv in part], rev
    raise CompactedError()


def scan_prefix(
    store, prefix: bytes, *, page: int = 5000, keys_only: bool = False
):
    """Streaming paginated scan, deliberately UNPINNED: each page reads
    the latest revision, so a long scan over a live cluster observes a
    moving snapshot but can never hit CompactedError mid-stream (a
    generator cannot restart after yielding).  Verification tools want
    crash-free approximate scans; the list+watch handoff wants
    list_prefix's pinned snapshot."""
    start, end = prefix, prefix_end(prefix)
    while True:
        res = store.range(start, end, limit=page, keys_only=keys_only)
        yield from res.kvs
        if not res.more or not res.kvs:
            return
        start = res.kvs[-1].key + b"\x00"


def drain_events(watcher, batch: int = 10000, limit: int = 200_000):
    """Yield queued events from a watcher (native or remote) until its
    queue momentarily empties OR ``limit`` events have been yielded.

    The limit is a liveness bound for tick-driven consumers: against a
    producer that sustains more than ``batch`` events per decode pass an
    unbounded drain would never return and the caller's cycle would
    starve.  The remainder stays queued (deep-capped watchers absorb it)
    and is picked up next cycle.
    """
    seen = 0
    while True:
        evs = watcher.poll(batch)
        for ev in evs:
            yield ev
        seen += len(evs)
        if len(evs) < batch or seen >= limit:
            return


def drain_events_light(watcher, batch: int = 10000, limit: int = 200_000):
    """drain_events, but yielding ``(type, key, value, mod_revision)``
    tuples (type 0=PUT, 1=DELETE).  Uses the watcher's poll_light when it
    has one; adapts full events otherwise (e.g. RemoteWatcher)."""
    poll = getattr(watcher, "poll_light", None)
    if poll is None:
        for ev in drain_events(watcher, batch, limit):
            yield (
                0 if ev.type == "PUT" else 1,
                ev.kv.key,
                ev.kv.value,
                ev.kv.mod_revision,
            )
        return
    seen = 0
    while True:
        evs = poll(batch)
        yield from evs
        seen += len(evs)
        if len(evs) < batch or seen >= limit:
            return


class MemStore:
    """In-process native store with etcd semantics.

    wal_dir=None disables the WAL; wal_mode in {none, buffered, fsync}
    mirrors the reference's --wal-default (reference main.rs:60-81);
    no_write_prefixes skips the WAL for hot non-durable prefixes like
    /registry/leases (reference --wal-no-write-prefix).
    """

    def __init__(
        self,
        wal_dir: str | None = None,
        wal_mode: str = "buffered",
        no_write_prefixes: tuple[str, ...] = (),
    ):
        lib = _lib()
        nwp = "\n".join(no_write_prefixes).encode()
        self._h = lib.ms_open(
            wal_dir.encode() if wal_dir else None, _WAL_MODES[wal_mode], nwp
        )
        if not self._h:
            raise RuntimeError("ms_open failed")

    def close(self) -> None:
        if self._h:
            _lib().ms_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- writes --------------------------------------------------------

    def _set(
        self,
        key: bytes,
        value: bytes | None,
        has_req: bool,
        req_is_version: bool,
        req_val: int,
        lease: int,
    ):
        lib = _lib()
        latest = ctypes.c_int64()
        cur = ctypes.POINTER(ctypes.c_uint8)()
        cur_len = ctypes.c_size_t()
        rev = lib.ms_set(
            self._h, key, len(key),
            value, 0 if value is None else len(value),
            1 if has_req else 0, 1 if req_is_version else 0, req_val, lease,
            ctypes.byref(latest), ctypes.byref(cur), ctypes.byref(cur_len),
        )
        if rev == _ERR_CAS:
            cur_kv = None
            if cur:
                data = _take_buf(lib, cur, cur_len)
                cur_kv, _ = _parse_kv(memoryview(data), 0)
            return False, latest.value, cur_kv
        return True, rev, None

    def put(self, key: bytes, value: bytes, lease: int = 0) -> int:
        ok, rev, _ = self._set(key, value, False, False, 0, lease)
        assert ok
        return rev

    def put_batch(
        self,
        items: list[tuple[bytes, bytes | None]],
        lease: int = 0,
    ) -> int:
        """Apply a wave of puts/deletes (value None = delete) in one native
        call under one lock acquisition; returns the last revision."""
        rev = self.put_frame(pack_put_frame(items), len(items), lease)
        if rev < 0:
            raise ValueError(f"ms_put_batch rc={rev}")
        return rev

    def put_frame(self, frame: bytes, count: int, lease: int = 0) -> int:
        """put_batch over a pre-packed frame (see pack_put_frame) — the
        wire batch path hands a client-packed frame straight through so
        the serving core does zero per-item Python.  Returns the last
        revision, or a negative MS_ERR_* code for a malformed frame (the
        native side bounds-checks every record)."""
        return _lib().ms_put_batch(self._h, frame, len(frame), count, lease)

    def bind_batch(
        self, binds: list[tuple[bytes, int, bytes]],
        exclude_watcher: int = -1,
    ) -> list[int]:
        """Splice spec.nodeName into stored pods under mod-revision CAS —
        the whole bind wave in one native call.  ``binds`` entries are
        (key, required_mod, node_name); returns per-entry new revision,
        or _ERR_CAS / _ERR_INVALID (caller falls back to the slow path).
        ``exclude_watcher`` suppresses the bind events on that one watcher
        (the issuing coordinator's own intake — see memstore.h)."""
        rc, results = self.bind_frame(
            pack_bind_frame(binds), len(binds), exclude_watcher
        )
        if rc < 0:
            raise ValueError(f"ms_bind_batch rc={rc}")
        return results

    def bind_frame(
        self, frame: bytes, count: int, exclude_watcher: int = -1
    ) -> tuple[int, list[int]]:
        """bind_batch over a pre-packed frame (see pack_bind_frame).
        Returns (bound_count_or_negative_error, per_record_revisions)."""
        lib = _lib()
        out = ctypes.POINTER(ctypes.c_int64)()
        rc = lib.ms_bind_batch(
            self._h, frame, len(frame), count, exclude_watcher,
            ctypes.byref(out)
        )
        if rc < 0:
            return rc, []
        results = out[:count]
        lib.ms_free(out)
        return rc, results

    def delete(self, key: bytes) -> tuple[int, bool]:
        """Returns (revision, deleted). Revision is 0 when nothing existed."""
        ok, rev, _ = self._set(key, None, False, False, 0, 0)
        assert ok
        return rev, rev > 0

    def cas(
        self,
        key: bytes,
        value: bytes | None,
        *,
        required_mod: int | None = None,
        required_version: int | None = None,
        lease: int = 0,
    ) -> tuple[bool, int, KeyValue | None]:
        """Txn-style compare-and-set: exactly the one Txn shape Kubernetes
        emits (reference kv_service.rs:126-337).  value=None deletes.
        Returns (ok, revision, current_kv_on_failure)."""
        if (required_mod is None) == (required_version is None):
            raise ValueError("exactly one of required_mod/required_version")
        is_ver = required_version is not None
        req = required_version if is_ver else required_mod
        return self._set(key, value, True, is_ver, req, lease)

    # ---- reads ---------------------------------------------------------

    def range(
        self,
        start: bytes,
        end: bytes | None = None,
        *,
        revision: int = 0,
        limit: int = 0,
        count_only: bool = False,
        keys_only: bool = False,
    ) -> RangeResult:
        lib = _lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = lib.ms_range(
            self._h, start, len(start),
            end, 0 if end is None else len(end),
            revision, limit, 1 if count_only else 0, 1 if keys_only else 0,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if rc == _ERR_COMPACTED:
            raise CompactedError(self.compact_revision)
        if rc == _ERR_FUTURE_REV:
            raise FutureRevError(f"revision {revision} > current")
        data = _take_buf(lib, out, out_len)
        buf = memoryview(data)
        rev, count, n, more = struct.unpack_from("<qqIB", buf, 0)
        off = 21
        kvs = []
        for _ in range(n):
            kv, off = _parse_kv(buf, off)
            kvs.append(kv)
        return RangeResult(rev, count, bool(more), kvs)

    def range_values(
        self,
        start: bytes,
        end: bytes | None = None,
        *,
        revision: int = 0,
        limit: int = 0,
    ) -> tuple[int, bool, list, bytes | None]:
        """``range`` minus everything but the value bytes: returns
        ``(revision, more, values, last_key)`` (``last_key`` feeds the
        pagination cursor).  Same wire frame, light parse — the per-KV
        KeyValue/key-bytes construction that dominates a million-row
        relist in Python is skipped (the range_light counterpart of
        poll_light)."""
        lib = _lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        rc = lib.ms_range(
            self._h, start, len(start),
            end, 0 if end is None else len(end),
            revision, limit, 0, 0,
            ctypes.byref(out), ctypes.byref(out_len),
        )
        if rc == _ERR_COMPACTED:
            raise CompactedError(self.compact_revision)
        if rc == _ERR_FUTURE_REV:
            raise FutureRevError(f"revision {revision} > current")
        data = _take_buf(lib, out, out_len)
        buf = memoryview(data)
        rev, _count, n, more = struct.unpack_from("<qqIB", buf, 0)
        off = 21
        values: list = []
        unpack = _KV_FIXED.unpack_from
        fixed = _KV_FIXED.size
        kend = klen = 0
        for _ in range(n):
            klen, vlen = unpack(buf, off)[:2]
            kend = off + fixed + klen
            off = kend + vlen
            values.append(bytes(buf[kend:off]))
        last_key = bytes(buf[kend - klen:kend]) if n else None
        return rev, bool(more), values, last_key

    def get(self, key: bytes, revision: int = 0) -> KeyValue | None:
        res = self.range(key, revision=revision)
        return res.kvs[0] if res.kvs else None

    # ---- watch ---------------------------------------------------------

    def watch(
        self,
        start: bytes,
        end: bytes | None = None,
        *,
        start_revision: int = 0,
        prev_kv: bool = False,
        queue_cap: int = 0,
    ) -> Watcher:
        """``queue_cap=0`` keeps the reference's 10K default (store.rs:27);
        tick-driven consumers that drain per cycle rather than
        continuously pass a deep cap so bursty churn between cycles
        doesn't overflow into a forced resync."""
        lib = _lib()
        compact = ctypes.c_int64()
        wid = lib.ms_watch_create(
            self._h, start, len(start),
            end, 0 if end is None else len(end),
            start_revision, 1 if prev_kv else 0, queue_cap,
            ctypes.byref(compact),
        )
        if wid == _ERR_COMPACTED:
            raise CompactedError(compact.value)
        return Watcher(self, wid)

    # ---- maintenance ---------------------------------------------------

    def compact(self, revision: int) -> None:
        rc = _lib().ms_compact(self._h, revision)
        if rc == _ERR_COMPACTED:
            raise CompactedError(self.compact_revision)
        if rc == _ERR_FUTURE_REV:
            raise FutureRevError(f"compact {revision} > current")

    def wal_sync(self) -> None:
        if _lib().ms_wal_sync(self._h) != 0:
            raise OSError("WAL sync failed")

    def stats(self) -> dict:
        import json

        lib = _lib()
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_size_t()
        lib.ms_stats_json(self._h, ctypes.byref(out), ctypes.byref(out_len))
        return json.loads(_take_buf(lib, out, out_len))

    @property
    def current_revision(self) -> int:
        return _lib().ms_current_revision(self._h)

    @property
    def compact_revision(self) -> int:
        return _lib().ms_compact_revision(self._h)

    @property
    def progress_revision(self) -> int:
        return _lib().ms_progress_revision(self._h)

    @property
    def num_keys(self) -> int:
        return _lib().ms_num_keys(self._h)

    @property
    def db_size(self) -> int:
        return _lib().ms_db_size(self._h)


class WireFront:
    """Native per-RPC etcd wire server over an in-process MemStore.

    The C++ answer to the asyncio server's per-unary-RPC interpreter
    cost: hand-rolled HTTP/2 + HPACK + the etcd protobuf subset,
    dispatching straight into the store on the event-loop thread
    (native/wirefront/wirefront.cc; the reference's equivalent surface
    is tonic in mem_etcd/src/main.rs:106-156).  Serves KV, Watch, Lease,
    Maintenance.Status and the k8s1m.BatchKV extension — the same
    contract as k8s1m_tpu.store.etcd_server, so either can back a
    cluster.
    """

    def __init__(self, store: MemStore, host: str = "127.0.0.1",
                 port: int = 0, threads: int = 1):
        self._h = _lib().wf_start(
            store._h, host.encode(), port, threads
        )
        if not self._h:
            raise RuntimeError(f"wf_start failed for {host}:{port}")
        self.port = _lib().wf_port(self._h)

    def close(self) -> None:
        if self._h:
            _lib().wf_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def wire_stress_put(host: str, port: int, count: int, concurrency: int = 64,
                    prefix: str = "/registry/leases/stress/", key_count: int = 10000,
                    val_len: int = 256) -> tuple[int, float]:
    """Native pipelined per-RPC Put load (client side of the standard
    etcd wire).  Returns (completed_puts, elapsed_seconds).  The client
    is C++ for the same reason the reference's stress-client is Rust
    (mem_etcd/stress-client): with one host core a Python client
    saturates long before any server does.
    """
    elapsed = ctypes.c_double()
    n = _lib().wf_stress_put(
        host.encode(), port, count, concurrency, prefix.encode(), key_count,
        val_len, ctypes.byref(elapsed),
    )
    if n < 0:
        raise RuntimeError(f"wf_stress_put failed rc={n}")
    return int(n), float(elapsed.value)
