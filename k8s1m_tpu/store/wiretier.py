"""wiretier — shared-frame watch encoding + wire delta compaction.

The watch tier's storm lane was encode-bound: every event batch was
proto-encoded once PER WATCH ID inside the fan-out pumps, so encode CPU
scaled with fan-out degree and the 100K-watch drill saturated one core
at ~4K delivered events/s.  The reference's mem_etcd wire discipline
(PAPER.md, state-store layer) encodes a frame once and fans the bytes
out — per-watch cost must scale with FRAMES, not with fan-out degree.

Three pieces, all byte-level:

- **Hand-composed WatchResponse framing** (``header_bytes`` /
  ``event_chunk`` / ``compose_frame``): protobuf serializes known
  fields in tag order, so a WatchResponse is exactly
  ``header-chunk + watch_id-varint + event-chunks`` — concatenating
  independently encoded parts is byte-identical to
  ``encode_event_batch(...).SerializeToString()``.  The differential in
  tests/test_watch_cache.py holds that identity; it is the license for
  every sharing trick below (clients can't tell composed frames from
  constructor-built ones).

- **The shared-frame extension**: when several watch ids on one stream
  owe the SAME batch, the tier ships ONE frame addressed to the first
  id and rides the remaining ids in trailing private fields
  (``SHARED_WIDS_FIELD``/``SHARED_FROM_REV_FIELD`` — high-numbered, so
  stock etcd clients parse them as unknown fields and see a normal
  single-watch response).  Our mux clients expand the tail with
  ``native.decode_shared_tail`` — index selection over shared bytes,
  never a re-encode.  ``SHARED_FROM_REV_FIELD`` declares a compacted
  frame's window lower bound (latest-per-key over [from_rev, to_rev]);
  to_rev is the last event's mod_revision.

- **``FrameTable``**: a bounded encode-once cache of per-event chunk
  bytes, keyed by the event's monotone ``seq`` (watch-cache tier) or an
  identity tuple (store server), so an event crossing N streams/lanes
  still costs ONE proto encode tier-wide.  Chunks are immutable after
  encode — see MIGRATION "Shared-frame wire contract" for what a new
  event field must do to stay shareable.

``SubscriptionMap`` is the replica fleet's consistent-hash key→replica
subscription map (tools/watch_scale.py): vnodes smooth the arcs, a dead
replica moves only its own arc, and survivors' subscriptions never
reshuffle — the property that makes replica warm-restart (resume from
revision via --resume-floor) a local event instead of a 100K-client
relist.
"""

from __future__ import annotations

import bisect
import collections
import hashlib

from k8s1m_tpu.obs.metrics import Counter
from k8s1m_tpu.store.proto import mvcc_pb2

_FRAME_ENCODES = Counter(
    "watchcache_frame_encodes_total",
    "event payloads proto-encoded into a shared frame table (each "
    "store event costs at most one encode tier-wide; fan-out reuses "
    "the bytes)", ()
)
_FRAME_HITS = Counter(
    "watchcache_frame_hits_total",
    "event chunk requests served from already-encoded shared-frame "
    "bytes — the encode CPU the wiretier elides; hits/(hits+encodes) "
    "is the table's share ratio", ()
)
_WIRE_BYTES = Counter(
    "watchcache_wire_bytes_total",
    "bytes of composed watch event frames put on the wire (a shared "
    "frame counts once regardless of how many watch ids ride it)", ()
)

# WatchResponse known-field tags (field number << 3 | wire type).
_TAG_HEADER = b"\x0a"      # field 1 (header), LEN
_TAG_WATCH_ID = b"\x10"    # field 2 (watch_id), varint
_TAG_EVENT = b"\x5a"       # field 11 (events), LEN
# The shared-frame extension: private trailing fields, high-numbered so
# they can never collide with WatchResponse's real fields and parse as
# preserved-but-ignored unknown fields in any stock protobuf client.
SHARED_WIDS_FIELD = 100    # repeated varint: extra watch ids sharing the frame
SHARED_FROM_REV_FIELD = 101  # varint: compaction window lower bound
_TAG_SHARED_WID = b"\xa0\x06"    # field 100, varint
_TAG_SHARED_FROM = b"\xa8\x06"   # field 101, varint


def varint(n: int) -> bytes:
    """Protobuf varint (unsigned LEB128).  Callers never pass negatives:
    the one negative watch id on the wire (-1 progress) stays on the
    ordinary proto-object path."""
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def header_bytes(header) -> bytes:
    """The response's leading header chunk (field 1)."""
    hb = header.SerializeToString()
    return _TAG_HEADER + varint(len(hb)) + hb


def event_chunk(payload: bytes) -> bytes:
    """Frame one serialized mvcc Event as a WatchResponse.events chunk."""
    return _TAG_EVENT + varint(len(payload)) + payload


def encode_event(ev) -> bytes:
    """One cache event's chunk bytes (duck-typed on CacheEvent's
    fields).  Byte-identical to what ``encode_event_batch`` would embed
    for the same event — the identity the wiretier differential gates."""
    return event_chunk(
        mvcc_pb2.Event(
            type=mvcc_pb2.Event.DELETE if ev.type else mvcc_pb2.Event.PUT,
            kv=mvcc_pb2.KeyValue(
                key=ev.key,
                value=ev.value,
                create_revision=ev.create_revision,
                mod_revision=ev.mod_revision,
                version=ev.version,
            ),
        ).SerializeToString()
    )


def compose_frame(hdr: bytes, wids, chunks, from_rev: int = 0) -> bytes:
    """One wire WatchResponse from pre-encoded parts.

    ``wids[0]`` is the frame's primary watch id (the known field);
    every further id rides the trailing shared-wid extension.  With a
    single wid and no ``from_rev`` the result is byte-identical to the
    constructor path — protobuf's canonical tag-order serialization is
    exactly this concatenation.  Frames are immutable once composed:
    sharing is index selection on the client, never a rewrite.
    """
    parts = [hdr]
    if wids[0]:
        parts.append(_TAG_WATCH_ID + varint(wids[0]))
    parts.extend(chunks)
    for wid in wids[1:]:
        parts.append(_TAG_SHARED_WID + varint(wid))
    if from_rev:
        parts.append(_TAG_SHARED_FROM + varint(from_rev))
    data = b"".join(parts)
    _WIRE_BYTES.inc(len(data))
    return data


def serialize_frame_or_message(m):
    """grpc response serializer for Watch streams that mix composed
    frames (already bytes) with ordinary proto control responses
    (created/canceled/progress)."""
    if isinstance(m, (bytes, bytearray, memoryview)):
        return m
    return m.SerializeToString()


class FrameTable:
    """Bounded encode-once cache of event chunk bytes.

    Keys are caller-chosen event identities (the watch-cache tier uses
    the event's monotone ``seq``; the store server an identity tuple);
    a falsy key opts out of caching (unit-test events without a seq).
    Eviction is FIFO by insertion, which tracks drain order closely
    enough that evicted entries are the already-fanned-out ones; a
    re-encode after eviction costs CPU, never correctness.
    """

    def __init__(self, cap: int = 8192):
        self.cap = max(1, cap)
        self._bytes: dict = {}
        # maxlen is a backstop only: the explicit eviction below keeps
        # the deque and dict exactly in sync before it could engage.
        self._order: collections.deque = collections.deque(maxlen=self.cap)

    def bytes_for(self, key, encode, *args) -> bytes:
        if key:
            b = self._bytes.get(key)
            if b is not None:
                _FRAME_HITS.inc()
                return b
        b = encode(*args)
        _FRAME_ENCODES.inc()
        if key:
            if len(self._order) >= self.cap:
                self._bytes.pop(self._order.popleft(), None)
            self._order.append(key)
            self._bytes[key] = b
        return b

    def __len__(self) -> int:
        return len(self._bytes)


class SubscriptionMap:
    """Consistent-hash key→replica subscription map for the watch
    fleet.

    Replicas are opaque ids (tools use tier indices).  Each replica
    plants ``vnodes`` points on a 64-bit blake2b ring; a key subscribes
    to the first replica point at-or-after its own hash.  Removing a
    replica (``without``) moves ONLY that replica's arcs to their ring
    successors: every surviving subscription is provably unchanged,
    which is what keeps a replica crash from reshuffling — and
    relisting — the whole fleet's watch population.

    Pure data structure: no locks, no I/O; safe to rebuild per topology
    change (the fleet is small, the key population is not).
    """

    def __init__(self, replicas, vnodes: int = 64):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("SubscriptionMap needs at least one replica")
        self.replicas = tuple(replicas)
        self.vnodes = vnodes
        ring = []
        for r in replicas:
            for v in range(vnodes):
                ring.append((self._point(b"%d#%d" % (r, v)), r))
        ring.sort()
        self._ring = ring
        self._points = [p for p, _ in ring]

    @staticmethod
    def _point(b: bytes) -> int:
        return int.from_bytes(
            hashlib.blake2b(b, digest_size=8).digest(), "big"
        )

    def replica_for(self, key: bytes) -> int:
        i = bisect.bisect_right(self._points, self._point(key))
        return self._ring[i % len(self._ring)][1]

    def without(self, replica: int) -> "SubscriptionMap":
        return SubscriptionMap(
            [r for r in self.replicas if r != replica], self.vnodes
        )
