"""Native control-plane store — the mem_etcd equivalent.

- ``native``      — ctypes bindings over the C++ core (native/memstore/).
- ``etcd_server`` — etcd v3 gRPC wire layer (KV/Watch/Lease/Maintenance),
                    the same API subset the reference serves
                    (reference mem_etcd/src/main.rs:106-153).
"""

from k8s1m_tpu.store.native import (
    INFINITY,
    CompactedError,
    FutureRevError,
    KeyValue,
    MemStore,
    RangeResult,
    WatchEvent,
    Watcher,
    prefix_end,
)

__all__ = [
    "INFINITY",
    "CompactedError",
    "FutureRevError",
    "KeyValue",
    "MemStore",
    "RangeResult",
    "WatchEvent",
    "Watcher",
    "prefix_end",
]
