"""etcd v3 gRPC wire layer over the native memstore.

This is the serving surface of the mem_etcd equivalent: the four services
the reference registers (reference mem_etcd/src/main.rs:106-109 — KV,
Watch, Lease, Maintenance) speaking the public etcd wire protocol, backed
by the C++ store (native/memstore).  Service semantics mirror the
reference component-for-component:

- **Txn supports exactly the one shape Kubernetes emits** — a single
  compare on MOD revision or VERSION, a single success Put-or-DeleteRange
  on the same key, an optional failure Range of the same key; anything
  else is InvalidArgument (reference mem_etcd/src/kv_service.rs:126-337).
- **Watch**: create -> ``created:true`` response, then past-changes batch,
  then a live loop delivering events in revision order, batched up to
  1000 per response (reference watch_service.rs:119-146); CancelRequest
  and ProgressRequest are handled, with the progress revision computed as
  max(store progress revision, last delivered) to close the same race the
  reference closes (watch_service.rs:172-176); a compacted start revision
  yields a response with ``compact_revision`` set (watch_service.rs:63-75).
  Event frames are composed from a per-stream shared chunk table
  (store/wiretier.py): an event fanning to several watches on one
  stream is proto-encoded once, and the composed bytes are
  byte-identical to the constructor path they replaced.
- **Lease is deliberately fake**: LeaseGrant returns an incrementing id
  and TTLs never expire — Kubernetes only uses etcd leases for Event TTLs
  (reference lease_service.rs:33-137, README.adoc:266-311).
- **Maintenance.Status** reports version "3.5.16" (>=3.5.13 so Kubernetes
  enables watch-progress support) and db size (reference
  maintenance_service.rs:29-117); Alarm/Defragment are stubs;
  Hash/Snapshot/MoveLeader are unimplemented, as in the reference.

The server writes a dummy key ``~`` on a fresh store so revisions start
at 1 exactly like etcd (reference main.rs:103-104).
"""

from __future__ import annotations

import asyncio
import logging
import time
import weakref

import grpc
from grpc import aio

from k8s1m_tpu.obs.metrics import CallbackMetric, Counter, Gauge, Histogram
from k8s1m_tpu.store import wiretier
from k8s1m_tpu.store.native import (
    CompactedError,
    FutureRevError,
    KeyValue,
    MemStore,
    WatchEvent,
    Watcher,
)
from k8s1m_tpu.store.proto import batch_pb2, mvcc_pb2, rpc_pb2

log = logging.getLogger("k8s1m.etcd")

ERR_COMPACTED = "etcdserver: mvcc: required revision has been compacted"
ERR_FUTURE_REV = "etcdserver: mvcc: required revision is a future revision"

_WATCH_BATCH = 1000          # events per WatchResponse (reference recv_many cap)
_WATCH_POLL_S = 0.005        # live-loop poll interval when idle

_REQ_COUNT = Counter(
    "memstore_requests_total", "gRPC requests by method", ("method",)
)
_REQ_LATENCY = Histogram(
    "memstore_request_seconds", "gRPC request latency by method", ("method",)
)
_STORE_GAUGE = Gauge("memstore_store", "Store-level gauges by stat", ("stat",))
_WATCH_COMPACT_CANCELS = Counter(
    "memstore_watch_compact_cancels_total",
    "watch creations canceled because start_revision predates the "
    "compaction window (client must relist, reflector-on-410)", ()
)
# Stores served with metrics enabled; the gauge aggregates over the live
# ones so a closed store neither pins memory nor clobbers stats.
_SERVED_STORES: weakref.WeakSet = weakref.WeakSet()
for _stat in ("num_keys", "db_size", "current_revision", "compact_revision"):
    _STORE_GAUGE.set_function(
        (lambda stat: lambda: sum(getattr(s, stat) for s in _SERVED_STORES))(_stat),
        stat=_stat.replace("current_", ""),
    )


# One scrape renders five callback metrics; without a snapshot each would
# re-serialize the full native stats JSON (taking the store read lock and
# inflating its own M_STATS counters five-fold).  A short TTL shares one
# snapshot across the metrics of a scrape without ever serving stale data
# to a real scrape interval (seconds).
_STATS_TTL_S = 0.25
_stats_snapshots: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _stats_of(s) -> dict:
    now = time.monotonic()
    ent = _stats_snapshots.get(s)
    if ent is not None and now - ent[0] < _STATS_TTL_S:
        return ent[1]
    st = s.stats()
    _stats_snapshots[s] = (now, st)
    return st


def _lock_samples(field: str, scale: float = 1.0):
    """Aggregate the native store's (method, structure, rw) lock cells
    across served stores (reference mem_etcd_lock_seconds/lock_count,
    metrics.rs:78-94)."""
    agg: dict[tuple, float] = {}
    for s in list(_SERVED_STORES):
        for cell in _stats_of(s).get("locks", ()):
            key = (cell["method"], cell["structure"], cell["rw"])
            agg[key] = agg.get(key, 0.0) + cell[field] * scale
    return [
        ({"method": m, "structure": st, "rw": rw}, v)
        for (m, st, rw), v in sorted(agg.items())
    ]


def _watch_samples(stat: str, agg=sum):
    vals = [
        _stats_of(s)["watch_pressure"][stat] for s in list(_SERVED_STORES)
    ]
    return [({}, agg(vals))] if vals else []


CallbackMetric(
    "memstore_lock_count_total",
    "store lock acquisitions by (method, structure, rw)",
    lambda: _lock_samples("count"), kind="counter",
)
CallbackMetric(
    "memstore_lock_wait_seconds_total",
    "time spent waiting on contended store locks",
    lambda: _lock_samples("wait_ns", 1e-9), kind="counter",
)
CallbackMetric(
    "memstore_watch_enqueued_total",
    "events enqueued to watcher queues",
    lambda: _watch_samples("enqueued"), kind="counter",
)
CallbackMetric(
    "memstore_watch_dropped_total",
    "events dropped at watcher queue caps (consumer must resync)",
    lambda: _watch_samples("dropped"), kind="counter",
)
CallbackMetric(
    "memstore_watch_queue_hwm",
    "high-water watcher queue depth",
    lambda: _watch_samples("queue_hwm", agg=max), kind="gauge",
)


def _kv_to_pb(kv: KeyValue) -> mvcc_pb2.KeyValue:
    return mvcc_pb2.KeyValue(
        key=kv.key,
        value=kv.value,
        create_revision=kv.create_revision,
        mod_revision=kv.mod_revision,
        version=kv.version,
        lease=kv.lease,
    )


def _encode_watch_event(ev: WatchEvent) -> bytes:
    """One native watch event as WatchResponse.events chunk bytes —
    byte-identical to the events.add()/CopyFrom path it replaced
    (protobuf serializes known fields in tag order)."""
    pb = mvcc_pb2.Event(
        type=(
            mvcc_pb2.Event.DELETE if ev.type == "DELETE"
            else mvcc_pb2.Event.PUT
        ),
        kv=_kv_to_pb(ev.kv),
    )
    if ev.prev_kv is not None:
        pb.prev_kv.CopyFrom(_kv_to_pb(ev.prev_kv))
    return wiretier.event_chunk(pb.SerializeToString())


class EtcdService:
    """All four etcd services over one MemStore."""

    def __init__(self, store: MemStore):
        self.store = store
        self._lease_id = 0
        self._lease_lock = asyncio.Lock()
        self._leases: dict[int, int] = {}  # id -> granted TTL (never expires)
        if store.current_revision == 0:
            # Fresh store: revisions must start at 1 like etcd.
            store.put(b"~", b"0")

    # ---- helpers -------------------------------------------------------

    def _header(self, revision: int | None = None) -> rpc_pb2.ResponseHeader:
        return rpc_pb2.ResponseHeader(
            cluster_id=1,
            member_id=1,
            revision=self.store.current_revision if revision is None else revision,
            raft_term=1,
        )

    @staticmethod
    def _end_of(req_end: bytes) -> bytes | None:
        return req_end if req_end else None

    # ---- KV ------------------------------------------------------------

    async def Range(self, req: rpc_pb2.RangeRequest, ctx) -> rpc_pb2.RangeResponse:
        _REQ_COUNT.inc(method="Range")
        with _REQ_LATENCY.time(method="Range"):
            try:
                res = self.store.range(
                    req.key,
                    self._end_of(req.range_end),
                    revision=req.revision,
                    limit=req.limit,
                    count_only=req.count_only,
                    keys_only=req.keys_only,
                )
            except CompactedError:
                await ctx.abort(grpc.StatusCode.OUT_OF_RANGE, ERR_COMPACTED)
            except FutureRevError:
                await ctx.abort(grpc.StatusCode.OUT_OF_RANGE, ERR_FUTURE_REV)
            return rpc_pb2.RangeResponse(
                header=self._header(res.revision),
                kvs=[_kv_to_pb(kv) for kv in res.kvs],
                more=res.more,
                count=res.count,
            )

    async def Put(self, req: rpc_pb2.PutRequest, ctx) -> rpc_pb2.PutResponse:
        _REQ_COUNT.inc(method="Put")
        with _REQ_LATENCY.time(method="Put"):
            if req.ignore_value or req.ignore_lease:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "ignore_value/ignore_lease not supported",
                )
            prev = self.store.get(req.key) if req.prev_kv else None
            rev = self.store.put(req.key, req.value, lease=req.lease)
            resp = rpc_pb2.PutResponse(header=self._header(rev))
            if prev is not None:
                resp.prev_kv.CopyFrom(_kv_to_pb(prev))
            return resp

    async def DeleteRange(
        self, req: rpc_pb2.DeleteRangeRequest, ctx
    ) -> rpc_pb2.DeleteRangeResponse:
        _REQ_COUNT.inc(method="DeleteRange")
        with _REQ_LATENCY.time(method="DeleteRange"):
            # NB: a multi-key range delete takes one revision per key (the
            # native store's set API is single-key, like the reference's
            # store.set — reference store.rs:189-382).  etcd proper uses a
            # single revision; Kubernetes never issues multi-key deletes on
            # its hot paths, so this divergence is accepted.
            prev_kvs = []
            if req.range_end:
                victims = self.store.range(
                    req.key, req.range_end, keys_only=not req.prev_kv
                ).kvs
                keys = [kv.key for kv in victims]
                if req.prev_kv:
                    prev_kvs = victims
            else:
                keys = [req.key]
                if req.prev_kv:
                    kv = self.store.get(req.key)
                    prev_kvs = [kv] if kv else []
            deleted = 0
            rev = self.store.current_revision
            for key in keys:
                r, ok = self.store.delete(key)
                if ok:
                    deleted += 1
                    rev = r
            return rpc_pb2.DeleteRangeResponse(
                header=self._header(rev),
                deleted=deleted,
                prev_kvs=[_kv_to_pb(kv) for kv in prev_kvs],
            )

    async def Txn(self, req: rpc_pb2.TxnRequest, ctx) -> rpc_pb2.TxnResponse:
        """The single Kubernetes Txn shape (reference kv_service.rs:126-337)."""
        _REQ_COUNT.inc(method="Txn")
        with _REQ_LATENCY.time(method="Txn"):
            if len(req.compare) != 1 or len(req.success) != 1 or len(req.failure) > 1:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "unsupported txn shape: want 1 compare, 1 success op, <=1 failure op",
                )
            cmp = req.compare[0]
            if cmp.result != rpc_pb2.Compare.EQUAL:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, "only EQUAL compares supported"
                )
            key = cmp.key
            if cmp.target == rpc_pb2.Compare.MOD:
                required_mod, required_version = cmp.mod_revision, None
            elif cmp.target == rpc_pb2.Compare.VERSION:
                required_mod, required_version = None, cmp.version
            else:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "only MOD/VERSION compare targets supported",
                )

            op = req.success[0]
            which = op.WhichOneof("request")
            if which == "request_put":
                if op.request_put.key != key:
                    await ctx.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "txn success op must target the compared key",
                    )
                value, lease = op.request_put.value, op.request_put.lease
            elif which == "request_delete_range":
                if op.request_delete_range.key != key or op.request_delete_range.range_end:
                    await ctx.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "txn delete must be single-key on the compared key",
                    )
                value, lease = None, 0
            else:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "txn success op must be Put or DeleteRange",
                )
            if req.failure:
                fail_op = req.failure[0]
                if (
                    fail_op.WhichOneof("request") != "request_range"
                    or fail_op.request_range.key != key
                ):
                    await ctx.abort(
                        grpc.StatusCode.INVALID_ARGUMENT,
                        "txn failure op must be a Range of the compared key",
                    )

            ok, rev, cur = self.store.cas(
                key,
                value,
                required_mod=required_mod,
                required_version=required_version,
                lease=lease,
            )
            resp = rpc_pb2.TxnResponse(header=self._header(rev if ok else None))
            resp.succeeded = ok
            if ok:
                rop = resp.responses.add()
                if which == "request_put":
                    rop.response_put.header.CopyFrom(self._header(rev))
                else:
                    rop.response_delete_range.header.CopyFrom(self._header(rev))
                    rop.response_delete_range.deleted = 1
            elif req.failure:
                rop = resp.responses.add()
                rop.response_range.header.CopyFrom(self._header())
                if cur is not None:
                    rop.response_range.kvs.append(_kv_to_pb(cur))
                    rop.response_range.count = 1
            return resp

    async def Compact(
        self, req: rpc_pb2.CompactionRequest, ctx
    ) -> rpc_pb2.CompactionResponse:
        _REQ_COUNT.inc(method="Compact")
        try:
            self.store.compact(req.revision)
        except CompactedError:
            await ctx.abort(grpc.StatusCode.OUT_OF_RANGE, ERR_COMPACTED)
        except FutureRevError:
            await ctx.abort(grpc.StatusCode.OUT_OF_RANGE, ERR_FUTURE_REV)
        return rpc_pb2.CompactionResponse(header=self._header())

    # ---- BatchKV (private pipelined-write extension, proto/batch.proto)

    async def PutFrame(
        self, req: batch_pb2.PutFrameRequest, ctx
    ) -> batch_pb2.PutFrameResponse:
        """A whole write wave as one native-format frame -> one FFI call.

        The asyncio interpreter cost (~300us/RPC) amortizes over the wave
        instead of repeating per put — the wire-side equivalent of the
        reference's per-core tonic workers (reference README.adoc:343-353).
        """
        _REQ_COUNT.inc(method="PutFrame")
        with _REQ_LATENCY.time(method="PutFrame"):
            # A record is >=8 bytes, so count must fit the frame; this
            # also keeps the client-controlled uint32 inside the FFI's
            # c_int before ctypes ever sees it.
            if req.count > len(req.frame) // 8:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "count exceeds frame capacity",
                )
            rev = self.store.put_frame(req.frame, req.count, req.lease)
            if rev < 0:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"malformed put frame (rc={rev})",
                )
            return batch_pb2.PutFrameResponse(revision=rev)

    async def BindFrame(
        self, req: batch_pb2.BindFrameRequest, ctx
    ) -> batch_pb2.BindFrameResponse:
        _REQ_COUNT.inc(method="BindFrame")
        with _REQ_LATENCY.time(method="BindFrame"):
            # A bind record is >=16 bytes (see PutFrame's count check).
            if req.count > len(req.frame) // 16:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "count exceeds frame capacity",
                )
            bound, revisions = self.store.bind_frame(req.frame, req.count)
            if bound < 0:
                await ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    f"malformed bind frame (rc={bound})",
                )
            return batch_pb2.BindFrameResponse(revisions=revisions, bound=bound)

    # ---- Watch ---------------------------------------------------------

    async def Watch(self, request_iterator, ctx):
        """Bidi watch stream: multiplexes many watches over one stream."""
        _REQ_COUNT.inc(method="Watch")
        watchers: dict[int, Watcher] = {}
        pumps: dict[int, asyncio.Task] = {}
        next_id = 1
        # Bounded reply queue (bounded-watch-buffer): a wedged client
        # socket backpressures this stream's pumps at the bound — their
        # native Watcher queues are themselves capped and cancel on
        # overflow — instead of buffering responses without limit.
        out: asyncio.Queue = asyncio.Queue(maxsize=1024)
        last_delivered = 0
        # Per-watch "delivered through" revision: every event <= cleared[wid]
        # matching the watch has been written to the stream.  Advances on
        # delivered events, and — for watches with nothing to say — on an
        # empty poll, using a revision snapshot taken BEFORE the poll (the
        # native queue is filled inside the store's write lock, so an empty
        # queue proves delivery through any revision committed before the
        # poll began).  This is what makes progress responses a true
        # barrier (etcd semantics: a progress notification promises the
        # client has seen everything at or below its revision).
        cleared: dict[int, int] = {}
        barriers: set = set()
        # Per-stream shared frame table (wiretier): an event fanning to
        # several watches on this stream is proto-encoded once, keyed
        # by its identity (prev_kv requests encode differently).
        ftable = wiretier.FrameTable(cap=4096)

        async def pump(wid: int, w: Watcher):
            nonlocal last_delivered
            loop = asyncio.get_running_loop()
            try:
                while True:
                    r0 = self.store.progress_revision
                    events = await loop.run_in_executor(
                        None, w.poll, _WATCH_BATCH, 0
                    )
                    if w.dropped:
                        # Queue overflow lost events; a silently gapped
                        # stream would corrupt client caches — cancel, as
                        # the store contract requires, so the client
                        # re-establishes from its last good revision.
                        w.cancel()
                        watchers.pop(wid, None)
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                canceled=True,
                                cancel_reason="watcher overflowed; events dropped",
                            )
                        )
                        return
                    if w.canceled and not events:
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                canceled=True,
                            )
                        )
                        return
                    if not events:
                        if cleared.get(wid, 0) < r0:
                            cleared[wid] = r0
                        await asyncio.sleep(_WATCH_POLL_S)
                        continue
                    chunks = [
                        ftable.bytes_for(
                            (ev.kv.mod_revision, ev.kv.key, ev.type,
                             ev.prev_kv is not None),
                            _encode_watch_event, ev,
                        )
                        for ev in events
                    ]
                    for ev in events:
                        last_delivered = max(last_delivered, ev.kv.mod_revision)
                    await out.put(
                        wiretier.compose_frame(
                            wiretier.header_bytes(self._header()),
                            [wid], chunks,
                        )
                    )
                    if cleared.get(wid, 0) < events[-1].kv.mod_revision:
                        cleared[wid] = events[-1].kv.mod_revision
            except asyncio.CancelledError:
                raise

        async def reader():
            nonlocal next_id
            async for req in request_iterator:
                which = req.WhichOneof("request_union")
                if which == "create_request":
                    cr = req.create_request
                    wid = cr.watch_id or next_id
                    next_id = max(next_id, wid) + 1
                    if wid in watchers:
                        # etcd rejects duplicate watch ids with a cancel
                        # response; silently replacing would orphan the old
                        # pump and leak its native event buffer.
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                canceled=True,
                                cancel_reason="duplicate watch_id",
                            )
                        )
                        continue
                    try:
                        w = self.store.watch(
                            cr.key,
                            self._end_of(cr.range_end),
                            start_revision=cr.start_revision,
                            prev_kv=cr.prev_kv,
                        )
                    except CompactedError as e:
                        _WATCH_COMPACT_CANCELS.inc()
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                created=True,
                                canceled=True,
                                compact_revision=e.compact_revision,
                            )
                        )
                        continue
                    watchers[wid] = w
                    await out.put(
                        rpc_pb2.WatchResponse(
                            header=self._header(), watch_id=wid, created=True
                        )
                    )
                    pumps[wid] = asyncio.create_task(pump(wid, w))
                elif which == "cancel_request":
                    wid = req.cancel_request.watch_id
                    w = watchers.pop(wid, None)
                    if w is not None:
                        w.cancel()
                        task = pumps.pop(wid, None)
                        if task:
                            task.cancel()
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(), watch_id=wid, canceled=True
                            )
                        )
                elif which == "progress_request":
                    # Progress must never regress below delivered events
                    # (reference watch_service.rs:172-176), and must not
                    # OVERTAKE them either: the response is a barrier —
                    # it goes out only after every watch on this stream
                    # has delivered through the progress revision (real
                    # etcd orders progress after prior events; the
                    # consistent-read-from-cache protocol depends on it).
                    rev = max(self.store.progress_revision, last_delivered)
                    t = asyncio.create_task(
                        progress_barrier(rev, list(watchers))
                    )
                    barriers.add(t)
                    t.add_done_callback(barriers.discard)
            await out.put(None)

        async def progress_barrier(rev: int, wids: list[int]) -> None:
            try:
                while not all(
                    wid not in watchers or cleared.get(wid, 0) >= rev
                    for wid in wids
                ):
                    await asyncio.sleep(_WATCH_POLL_S)
                await out.put(
                    rpc_pb2.WatchResponse(
                        header=self._header(rev), watch_id=-1
                    )
                )
            except asyncio.CancelledError:
                raise

        rtask = asyncio.create_task(reader())
        try:
            while True:
                resp = await out.get()
                if resp is None:
                    return
                yield resp
        finally:
            rtask.cancel()
            for task in pumps.values():
                task.cancel()
            for task in list(barriers):
                task.cancel()
            for w in watchers.values():
                w.cancel()

    # ---- Lease (deliberately fake, reference lease_service.rs) ---------

    async def LeaseGrant(self, req: rpc_pb2.LeaseGrantRequest, ctx):
        _REQ_COUNT.inc(method="LeaseGrant")
        async with self._lease_lock:
            self._lease_id += 1
            lid = req.ID or self._lease_id
            self._leases[lid] = req.TTL
        return rpc_pb2.LeaseGrantResponse(
            header=self._header(), ID=lid, TTL=req.TTL
        )

    async def LeaseRevoke(self, req: rpc_pb2.LeaseRevokeRequest, ctx):
        _REQ_COUNT.inc(method="LeaseRevoke")
        self._leases.pop(req.ID, None)
        return rpc_pb2.LeaseRevokeResponse(header=self._header())

    async def LeaseKeepAlive(self, request_iterator, ctx):
        async for req in request_iterator:
            yield rpc_pb2.LeaseKeepAliveResponse(
                header=self._header(),
                ID=req.ID,
                TTL=self._leases.get(req.ID, 0),
            )

    async def LeaseTimeToLive(self, req: rpc_pb2.LeaseTimeToLiveRequest, ctx):
        ttl = self._leases.get(req.ID)
        if ttl is None:
            return rpc_pb2.LeaseTimeToLiveResponse(
                header=self._header(), ID=req.ID, TTL=-1
            )
        return rpc_pb2.LeaseTimeToLiveResponse(
            header=self._header(), ID=req.ID, TTL=ttl, grantedTTL=ttl
        )

    async def LeaseLeases(self, req: rpc_pb2.LeaseLeasesRequest, ctx):
        return rpc_pb2.LeaseLeasesResponse(
            header=self._header(),
            leases=[rpc_pb2.LeaseStatus(ID=lid) for lid in self._leases],
        )

    # ---- Maintenance ---------------------------------------------------

    async def Status(self, req: rpc_pb2.StatusRequest, ctx):
        return rpc_pb2.StatusResponse(
            header=self._header(),
            version="3.5.16",
            dbSize=self.store.db_size,
            dbSizeInUse=self.store.db_size,
            leader=1,
            raftIndex=1,
            raftTerm=1,
        )

    async def Alarm(self, req: rpc_pb2.AlarmRequest, ctx):
        return rpc_pb2.AlarmResponse(header=self._header())

    async def Defragment(self, req: rpc_pb2.DefragmentRequest, ctx):
        return rpc_pb2.DefragmentResponse(header=self._header())

    async def Hash(self, req, ctx):
        await ctx.abort(grpc.StatusCode.UNIMPLEMENTED, "Hash not implemented")

    async def Snapshot(self, req, ctx):
        await ctx.abort(grpc.StatusCode.UNIMPLEMENTED, "Snapshot not implemented")
        yield  # pragma: no cover — makes this an async generator

    async def MoveLeader(self, req, ctx):
        await ctx.abort(grpc.StatusCode.UNIMPLEMENTED, "MoveLeader not implemented")


def _unary(fn, req_cls, resp_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


def _stream_stream(fn, req_cls, resp_cls):
    return grpc.stream_stream_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


def _unary_stream(fn, req_cls, resp_cls):
    return grpc.unary_stream_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


def add_services(server: aio.Server, svc: EtcdService) -> None:
    pb = rpc_pb2
    kv = {
        "Range": _unary(svc.Range, pb.RangeRequest, pb.RangeResponse),
        "Put": _unary(svc.Put, pb.PutRequest, pb.PutResponse),
        "DeleteRange": _unary(
            svc.DeleteRange, pb.DeleteRangeRequest, pb.DeleteRangeResponse
        ),
        "Txn": _unary(svc.Txn, pb.TxnRequest, pb.TxnResponse),
        "Compact": _unary(svc.Compact, pb.CompactionRequest, pb.CompactionResponse),
    }
    watch = {
        # Event frames leave the pumps pre-composed (wiretier shared
        # chunk bytes); control responses stay proto objects.
        "Watch": grpc.stream_stream_rpc_method_handler(
            svc.Watch,
            request_deserializer=pb.WatchRequest.FromString,
            response_serializer=wiretier.serialize_frame_or_message,
        ),
    }
    lease = {
        "LeaseGrant": _unary(svc.LeaseGrant, pb.LeaseGrantRequest, pb.LeaseGrantResponse),
        "LeaseRevoke": _unary(
            svc.LeaseRevoke, pb.LeaseRevokeRequest, pb.LeaseRevokeResponse
        ),
        "LeaseKeepAlive": _stream_stream(
            svc.LeaseKeepAlive, pb.LeaseKeepAliveRequest, pb.LeaseKeepAliveResponse
        ),
        "LeaseTimeToLive": _unary(
            svc.LeaseTimeToLive, pb.LeaseTimeToLiveRequest, pb.LeaseTimeToLiveResponse
        ),
        "LeaseLeases": _unary(
            svc.LeaseLeases, pb.LeaseLeasesRequest, pb.LeaseLeasesResponse
        ),
    }
    maint = {
        "Alarm": _unary(svc.Alarm, pb.AlarmRequest, pb.AlarmResponse),
        "Status": _unary(svc.Status, pb.StatusRequest, pb.StatusResponse),
        "Defragment": _unary(svc.Defragment, pb.DefragmentRequest, pb.DefragmentResponse),
        "Hash": _unary(svc.Hash, pb.HashRequest, pb.HashResponse),
        "Snapshot": _unary_stream(svc.Snapshot, pb.SnapshotRequest, pb.SnapshotResponse),
        "MoveLeader": _unary(svc.MoveLeader, pb.MoveLeaderRequest, pb.MoveLeaderResponse),
    }
    batch = {
        "PutFrame": _unary(
            svc.PutFrame, batch_pb2.PutFrameRequest, batch_pb2.PutFrameResponse
        ),
        "BindFrame": _unary(
            svc.BindFrame, batch_pb2.BindFrameRequest, batch_pb2.BindFrameResponse
        ),
    }
    for name, handlers in (
        ("etcdserverpb.KV", kv),
        ("etcdserverpb.Watch", watch),
        ("etcdserverpb.Lease", lease),
        ("etcdserverpb.Maintenance", maint),
        ("k8s1m.BatchKV", batch),
    ):
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(name, handlers),)
        )


async def serve(
    store: MemStore,
    port: int = 2379,
    host: str = "127.0.0.1",
    metrics_port: int = 0,
) -> tuple[aio.Server, int]:
    """Start the etcd-compatible server; returns (server, bound_port)."""
    server = aio.server(
        options=[
            # Mirror the reference's HTTP/2 tuning (main.rs:145-147).
            ("grpc.max_concurrent_streams", 100),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ]
    )
    add_services(server, EtcdService(store))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise OSError(f"failed to bind {host}:{port} (port in use?)")
    await server.start()
    if metrics_port:
        from k8s1m_tpu.obs.http import start_metrics_server

        _SERVED_STORES.add(store)
        start_metrics_server(metrics_port)
    return server, bound
