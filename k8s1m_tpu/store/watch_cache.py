"""Watch-cache fan-out tier — the apiserver's watch-amplification role.

The reference's hardest apiserver findings live in this tier: every
kubelet+kube-proxy holds ~18 apiserver watches (18M watches at 1M nodes)
and **none of them reach etcd** — the apiserver's watch cache holds one
etcd watch per resource and fans events out to all client watches
(reference README.adoc:410-416).  The cache's storage structure sets the
update ceiling: the 1.31+ B-tree cache capped at ~40K updates/s while the
O(1) hashmap cache sustained 100K+/s, which is why the reference runs a
custom k3s build with ``BtreeWatchCache=false`` (reference
README.adoc:495-499, terraform/k8s-server/server.tf:39).

This module is that tier for our store: ``WatchCache`` primes itself with
a list+watch against the upstream store (ONE store watch per prefix,
regardless of client count) and serves the public etcd Watch wire
protocol downstream, so ``EtcdClient``/``watch_stress`` work against it
unchanged.  ``index="hash"|"btree"`` switches the cached-object storage
to reproduce the ceiling experiment: hash keeps an O(1) dict, btree
additionally maintains the ordered key index on every event (bisect
search + ordered insert), which is also what lets btree-mode Range serve
ordered lists without a per-call sort.

Downstream watch semantics mirror the store server
(k8s1m_tpu/store/etcd_server.py): created:true response, past-events
replay from the bounded history window, live batches, ProgressRequest,
CancelRequest; a start revision older than the window yields a cancel
response with ``compact_revision`` set.

Storm-proofing (ISSUE 15 watchplane) — the tier degrades instead of
detonating:

- **Resume-from-revision.**  An upstream watch break no longer cancels
  every client for a full relist: ``reprime`` diffs the relisted
  snapshot against the cached objects and replays the NET difference
  (latest value per changed key, deletes stamped at the relist
  revision) through the ordinary fan-out, so clients keep their watches
  across the outage (``watchcache_resumes_total``).  Only when the net
  diff cannot fit the bounded history window does the tier fall back to
  the old cancel-everyone hammer (``watchcache_invalidations_total``).
  Net replay is legal because the tier's consumers are level-triggered
  caches (see MIGRATION "Watch resume & degradation contract").

- **Bounded-lag degradation.**  A slow consumer is coalesced before it
  is canceled: each subscriber buffers FIFO up to the tier's effective
  lag budget, then folds further events latest-only-per-key
  (``watchcache_coalesced_events_total``) — the deepest-backlog (i.e.
  floodiest) watchers degrade first, and the loadshed HealthController
  shrinks the budget tier-wide as total backlog climbs
  (HEALTHY -> full, DEGRADED -> quarter, SHEDDING -> coalesce
  immediately).  Only a subscriber whose coalesce map ALSO overflows
  its hard cap is canceled so it relists.

- **Sharded fan-out pumps.**  Dispatch splits across N pump lanes
  (watcher id hash) with a bounded per-stream output queue, so one
  wedged subscriber socket backpressures its own lane instead of
  head-of-line-blocking every watcher, and a 100K-watch stream costs N
  tasks, not 100K.

- **Shared-frame wire encoding (wiretier, ISSUE 20).**  Fan-out used
  to re-encode every batch per watch id; the pump sweeps now group
  watchers owing identical batches (equal ``CacheEvent.seq`` tuples),
  fetch each event's chunk bytes once from a tier-level
  ``wiretier.FrameTable``, and ship ONE composed frame per group with
  the extra watch ids riding a trailing extension — encode CPU scales
  with frames, not fan-out degree, and wire bytes drop by the realized
  sharing degree.  A coalesced drain additionally declares its
  compacted [from_rev, to_rev] window on the wire.  Per-watch streams
  stay byte-identical to the unshared encoding (the
  tests/test_watch_cache.py wiretier differentials).

- **Replica warm restart (``--resume-floor``).**  A relaunched fleet
  replica primes at the current revision, catch-up loads
  (floor, prime_rev] into the history window from store history before
  binding its port, and the dead instance's clients re-attach with
  their own start_revision and RESUME (``watchcache_resumes_total``)
  instead of relisting — see ``run_upstream``.

- **Faultline hooks** ``watch.tier/pump.stall`` and
  ``watch.tier/subscriber.send`` (plus the existing ``upstream.recv``)
  make all three failure modes injectable by seed — the watchstorm
  drill (tools/watch_fanout_ab.py) gates delivery-lag p99, zero event
  loss by ledger, and the resume rate under the composed storm.
"""

from __future__ import annotations

import argparse
import asyncio
import bisect
import collections
import dataclasses
import hmac
import json
import logging
import zlib

import grpc
from grpc import aio

from k8s1m_tpu import faultline
from k8s1m_tpu.faultline import InjectedFault, policy_for
from k8s1m_tpu.lint import THREAD_OWNER, guarded_by
from k8s1m_tpu.loadshed import HealthController, LoadshedConfig, Signals
from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.store import wiretier
from k8s1m_tpu.store.etcd_client import EtcdClient
from k8s1m_tpu.store.native import prefix_end
from k8s1m_tpu.store.proto import mvcc_pb2, rpc_pb2

log = logging.getLogger("k8s1m.watchcache")

_EVENTS_IN = Counter(
    "watchcache_events_in_total", "events received from the store", ()
)
_EVENTS_OUT = Counter(
    "watchcache_events_delivered_total", "events delivered to client watches", ()
)
_WATCHERS = Gauge("watchcache_watchers", "active client watches", ())
_INVALIDATIONS = Counter(
    "watchcache_invalidations_total",
    "upstream watch breaks that canceled every client for relist", ()
)
_REPLAYS = Counter(
    "watchcache_replays_total",
    "follower resume-from-revision requests, by outcome: resumed = the "
    "history window reached the requested revision (warm-standby "
    "follow mode rides this); compact_relist = the window fell short "
    "and the client was told to relist",
    ("outcome",),
)
_RESUMES = Counter(
    "watchcache_resumes_total",
    "upstream watch breaks absorbed by diff-replay resume: clients "
    "kept their watches, the net snapshot difference was replayed "
    "(the split's other half is watchcache_invalidations_total)", ()
)
_COALESCED = Counter(
    "watchcache_coalesced_events_total",
    "events elided by per-subscriber latest-only-per-key coalescing "
    "under the bounded-lag budget", ()
)
_DEGRADED_WATCHERS = Gauge(
    "watchcache_degraded_watchers",
    "client watches currently in coalescing (bounded-lag) delivery", ()
)

_DEFAULT_WINDOW = 65536

# Priming list page size: bounds any single upstream response to a few
# MB regardless of prefix population (client-go chunking equivalent).
_PRIME_PAGE = 10_000
_QUEUE_CAP = 10_000
_WATCH_BATCH = 1000
# Per-subscriber FIFO budget before latest-only coalescing engages
# (the loadshed controller shrinks it tier-wide under backlog).
_LAG_BUDGET = 4096
# Per-stream output queue bound: a wedged subscriber socket
# backpressures its own stream's pump lanes at this depth instead of
# buffering responses without bound.
_OUT_CAP = 1024
# Fan-out pump lanes per Watch stream (watcher id hash).
_PUMP_SHARDS = 8
# Bounded stall applied at the pump.stall hook when the firing spec
# carries no delay of its own.
_STALL_S = 0.05


@dataclasses.dataclass
class CachedObject:
    value: bytes
    create_revision: int
    mod_revision: int
    version: int


@dataclasses.dataclass
class CacheEvent:
    type: int            # 0 PUT, 1 DELETE
    key: bytes
    value: bytes
    create_revision: int
    mod_revision: int
    version: int
    # Tier-monotone apply sequence (0 = never applied, e.g. test-built
    # events): the shared-frame table's cache key and the pump sweep's
    # batch-identity fingerprint (equal seq tuples = equal bytes owed).
    seq: int = 0


class Downstream:
    """One client watch served from the cache.

    Delivery runs in two regimes: a bounded FIFO queue up to the tier's
    effective lag budget, then latest-only-per-key coalescing — legal
    for the level-triggered caches this tier serves (the net state at
    quiesce is identical to the uncoalesced stream; the differential
    gate in tests/test_watch_cache.py holds it).  Once coalescing
    engages it sticks until the subscriber fully drains, so emission
    stays revision-ordered (everything in the map postdates everything
    in the queue).  Only a coalesce map overflowing ``hard_cap``
    distinct keys cancels the watch (the client relists) — the old
    cancel-at-queue-cap hammer demoted to the last resort.
    """

    def __init__(self, wid: int, key: bytes, end: bytes | None,
                 min_rev: int = 0, hard_cap: int = _QUEUE_CAP):
        self.id = wid
        self.key = key
        self.end = end          # None = single key; b"\0" = to infinity
        self.min_rev = min_rev  # suppress live events below this revision
        self.service_id = wid   # stream-side watch id (service assigns)
        self.hard_cap = hard_cap
        # Explicit bound (bounded-watch-buffer): coalescing engages at
        # the (smaller) effective lag budget, so maxlen is a never-hit
        # backstop, not the working limit.
        self.queue: collections.deque[CacheEvent] = collections.deque(
            maxlen=hard_cap
        )
        self.coalesced: dict[bytes, CacheEvent] = {}
        self.coalescing = False
        # Newest mod_revision handed to this watch — the delivery
        # high-water mark the byte-identity differential asserts
        # against (tests/test_watch_cache.py); not read on any
        # production path.
        self.last_pushed = 0
        # True when the newest pop_batch drained from the coalesce map:
        # that batch is a compacted [from_rev, to_rev] window
        # (latest-per-key), which the wiretier declares on the wire.
        self.last_pop_compacted = False
        self.wakeup = asyncio.Event()
        self.overflowed = False
        self.owner: "WatchCache | None" = None   # set by register()
        self.on_ready = None    # pump-shard callback (service side)
        self._ready = False     # latched onto a shard's ready set

    def matches(self, key: bytes) -> bool:
        if self.end is None:
            return key == self.key
        if key < self.key:
            return False
        if self.end == b"\x00":
            return True
        return key < self.end

    def push(self, ev: CacheEvent, lag_budget: int | None = None) -> None:
        budget = self.hard_cap if lag_budget is None else lag_budget
        if self.coalescing or len(self.queue) >= budget:
            if not self.coalescing:
                self.coalescing = True
                _DEGRADED_WATCHERS.inc()
            if ev.key in self.coalesced:
                # Latest-only elision: the superseded event is the one
                # a level-triggered consumer never needed.
                _COALESCED.inc()
                self.coalesced[ev.key] = ev
            elif len(self.coalesced) >= self.hard_cap:
                # Even latest-per-key cannot keep up (more distinct
                # keys lagging than the hard cap): cancel rather than
                # gap silently — the client relists.
                self.overflowed = True
            else:
                self.coalesced[ev.key] = ev
                if self.owner is not None:
                    self.owner._backlog += 1
        else:
            self.queue.append(ev)
            if self.owner is not None:
                self.owner._backlog += 1
        if ev.mod_revision > self.last_pushed:
            self.last_pushed = ev.mod_revision
        self._notify()

    def pop_batch(self, n: int) -> list[CacheEvent]:
        """Drain up to ``n`` events in revision order: the FIFO first,
        then the coalesce map (all of whose events postdate the
        queue's, since coalescing sticks until fully drained)."""
        out: list[CacheEvent] = []
        q = self.queue
        self.last_pop_compacted = False
        while q and len(out) < n:
            out.append(q.popleft())
        if not q and self.coalesced and len(out) < n:
            self.last_pop_compacted = True
            # seq tiebreak: reprime stamps several events at one wire
            # revision, and peers coalescing the same window must pop
            # identical batches for the sweep to share their frame.
            rest = sorted(
                self.coalesced.values(),
                key=lambda e: (e.mod_revision, e.seq),
            )
            take = rest[: n - len(out)]
            for e in take:
                del self.coalesced[e.key]
            out.extend(take)
            if not self.coalesced:
                self.coalescing = False
                _DEGRADED_WATCHERS.dec()
        if self.owner is not None:
            self.owner._backlog -= len(out)
        return out

    @property
    def backlog(self) -> int:
        return len(self.queue) + len(self.coalesced)

    def _notify(self) -> None:
        self.wakeup.set()
        cb = self.on_ready
        if cb is not None:
            cb(self)


@guarded_by(
    # The cache is event-loop-confined by design: the upstream pump, the
    # downstream reader tasks and every Range all run on one asyncio
    # loop.  THREAD_OWNER makes that a checked invariant — a second
    # event loop (or a bare thread) reaching into the cache is exactly
    # the corruption an async tier makes easy to write and hard to see.
    objects=THREAD_OWNER,
    sorted_keys=THREAD_OWNER,
    history=THREAD_OWNER,
    _exact=THREAD_OWNER,
    _ranges=THREAD_OWNER,
    _backlog=THREAD_OWNER,
    _lag_now=THREAD_OWNER,
    _seq=THREAD_OWNER,
)
class WatchCache:
    """Cached objects + bounded event history + downstream fan-out."""

    def __init__(
        self, index: str = "hash", window: int = _DEFAULT_WINDOW,
        lag_budget: int = _LAG_BUDGET,
        shed: HealthController | None = None,
    ):
        if index not in ("hash", "btree"):
            raise ValueError(f"index must be hash|btree, got {index!r}")
        self.index = index
        self.objects: dict[bytes, CachedObject] = {}
        # btree mode: ordered key index maintained per event — the
        # reference's BtreeWatchCache cost axis.  hash mode sorts only
        # when a Range needs it.
        self.sorted_keys: list[bytes] = []
        self.history: collections.deque[CacheEvent] = collections.deque(
            maxlen=window
        )
        self.last_revision = 0    # newest applied store revision
        self.prime_revision = 0   # revision of the (latest) priming list
        self.events_in = 0
        self.events_out = 0
        # Watcher index: exact-key hashmap + (short) list of range
        # watchers, so per-event dispatch is O(1) + O(range watchers) —
        # the fan-out stays cheap even with 10K+ exact watchers (the
        # 18-watches-per-node shape is mostly exact watches).
        self._exact: dict[bytes, set[Downstream]] = {}
        self._ranges: set[Downstream] = set()
        self._next_id = 1
        # Bounded-lag degradation: the health controller watches total
        # fan-out backlog (every subscriber's queued + coalesced
        # events, maintained incrementally) and derives the effective
        # per-subscriber FIFO budget — degradation is controller-driven
        # and depth-triggered, so under a lease flood the floodiest
        # (deepest-backlog) watchers degrade first.
        self.lag_budget = lag_budget
        self._shed = shed or HealthController(
            LoadshedConfig(
                queue_degraded=16 * lag_budget,
                queue_shed=64 * lag_budget,
                queue_cap=1 << 30,
                queue_recover=4 * lag_budget,
                recover_cycles=2,
            ),
            name="watch.tier",
        )
        self._backlog = 0
        self._lag_now = lag_budget
        # Monotone apply counter: stamps CacheEvent.seq, the shared
        # frame table's encode-once cache key.
        self._seq = 0

    def loadshed_tick(self) -> None:
        """Feed the current fan-out backlog to the tier's health
        controller and refresh the effective lag budget.  Ticked by the
        upstream pump once per applied batch: decisions only matter
        while events are flowing, so a quiet prefix's stale budget is
        harmless until traffic (and with it ticking) resumes."""
        self._shed.tick(Signals(queue_depth=self._backlog))
        self._lag_now = self._shed.lag_budget(self.lag_budget)

    # ---- window bounds -------------------------------------------------

    @property
    def replayable_from(self) -> int:
        """Earliest revision from which event replay is provably
        complete: everything after the priming list is in the history
        window unless the bounded deque has started evicting."""
        if self.history and len(self.history) == self.history.maxlen:
            return self.history[0].mod_revision
        return self.prime_revision + 1

    # ---- upstream apply ------------------------------------------------

    def prime(self, kvs, revision: int) -> None:
        """Load the initial list snapshot (list+watch priming)."""
        for kv in kvs:
            self.objects[kv.key] = CachedObject(
                kv.value, kv.create_revision, kv.mod_revision, kv.version
            )
        if self.index == "btree":
            self.sorted_keys = sorted(self.objects)
        self.last_revision = max(self.last_revision, revision)
        self.prime_revision = max(self.prime_revision, revision)

    def invalidate(self, key: bytes = b"", end: bytes = b"\x00") -> None:
        """The cancel-everyone hammer, now the FALLBACK: an upstream
        outage whose net effect cannot be represented in the bounded
        history window (see ``reprime``) cancels every client watch so
        each one relists — the same contract as a store-watcher
        overflow — and resets state for re-priming.

        ``[key, end)`` scopes the OBJECT clearing to the broken
        stream's prefix: a healthy prefix's objects stay, so its
        cache-served Ranges don't turn confidently empty while only
        another prefix's stream is down.  Watcher cancellation and the
        history window stay global — the ring is shared, and with it
        cleared every replay window resets, so a kept prefix's
        relisting clients converge through compact-cancel + relist."""
        n = sum(len(p) for p in self._exact.values()) + len(self._ranges)
        log.warning(
            "cache invalidated at revision %d: canceling %d client "
            "watches for relist", self.last_revision, n,
        )
        _INVALIDATIONS.inc()
        for peers in self._exact.values():
            for w in peers:
                w.overflowed = True
                w._notify()
        for w in self._ranges:
            w.overflowed = True
            w._notify()
        if not key and end == b"\x00":
            self.objects.clear()
            self.sorted_keys = []
        else:
            for k in [
                k for k in self.objects
                if k >= key and (end == b"\x00" or k < end)
            ]:
                del self.objects[k]
            if self.index == "btree":
                self.sorted_keys = sorted(self.objects)
        self.history.clear()

    def reprime(
        self, kvs, revision: int,
        key: bytes = b"", end: bytes = b"\x00",
    ) -> bool:
        """Resume path after an upstream break: diff the relisted
        snapshot (``kvs`` at ``revision``) against the cached objects
        and replay the NET difference — latest value per changed key,
        one DELETE per vanished key — through the ordinary fan-out, so
        every client watch survives the outage in place.

        ``[key, end)`` scopes the deletion sweep to the prefix the
        broken stream actually covered: the object map is the UNION of
        every watched prefix, and an unscoped diff would read every
        other prefix's keys as deleted (found by the storm drill's
        idle-watch population, which must deliver nothing, ever).

        Deletes lost with the stream have no knowable revision; they
        are stamped at the relist revision (an upper bound).  Keys
        created AND deleted inside the outage are invisible in both
        snapshots and produce nothing.  Both are exactly the latest-
        only elisions coalescing already performs, and legal for the
        same reason: the tier's consumers are level-triggered caches,
        and the net state at quiesce is byte-identical to a full
        relist (the tests/test_watch_cache.py differential).

        Every replayed event goes out ON THE WIRE stamped at the
        relist revision (>= the tier's global header revision by store
        monotonicity): a client whose last-seen revision came from a
        header on ANOTHER prefix's progress re-attaches with a
        start_revision a back-dated event would never clear (review
        catch — the delete-stamping rationale applies to the puts
        too).  The object map keeps the true MVCC revisions so the
        NEXT reprime's diff still compares real facts.

        Returns True when clients were resumed; False when the net
        diff exceeds the bounded history window — appending it would
        evict genuine history under replaying followers' feet — and
        the tier fell back to ``invalidate()`` (counted there)."""
        changed: list[CacheEvent] = []
        new_keys = set()
        for kv in kvs:
            new_keys.add(kv.key)
            old = self.objects.get(kv.key)
            if old is not None and kv.mod_revision < old.mod_revision:
                # Per-key revision ROLLBACK: the store restarted having
                # lost its tail (buffered-WAL crash).  Forward
                # net-replay cannot represent history moving backwards
                # — fail closed to the hammer, the old contract.
                self.invalidate(key, end)
                return False
            if old is None or old.mod_revision != kv.mod_revision:
                changed.append(CacheEvent(
                    0, kv.key, kv.value, kv.create_revision,
                    kv.mod_revision, kv.version,
                ))
        deleted = []
        local_max = 0       # this PREFIX's cached high-water revision
        for k, o in self.objects.items():
            if k < key or not (end == b"\x00" or k < end):
                continue
            if o.mod_revision > local_max:
                local_max = o.mod_revision
            if k not in new_keys:
                deleted.append(k)
        if revision < local_max:
            # The whole-prefix rollback form of the same story: the
            # relist pins a revision BELOW state this prefix already
            # held.  Judged against the PREFIX-LOCAL high-water mark,
            # not the cache's global last_revision — on a multi-prefix
            # tier a healthy prefix's live events advance the global
            # mark past a broken prefix's relist pin as a matter of
            # course, and that must not read as a rollback.
            self.invalidate(key, end)
            return False
        if len(changed) + len(deleted) > (self.history.maxlen or 0):
            self.invalidate(key, end)
            return False
        for k in deleted:
            changed.append(CacheEvent(1, k, b"", 0, revision, 0))
        changed.sort(key=lambda e: e.mod_revision)
        for e in changed:
            self.apply(
                e.type, e.key, e.value, e.create_revision,
                e.mod_revision, e.version, wire_revision=revision,
            )
        self.last_revision = max(self.last_revision, revision)
        _RESUMES.inc()
        self.loadshed_tick()
        log.info(
            "upstream resumed at revision %d: %d net event(s) replayed "
            "to %d client watch(es), no relists",
            revision, len(changed), self.watcher_count,
        )
        return True

    def apply(self, ev_type: int, key: bytes, value: bytes,
              create_revision: int, mod_revision: int, version: int,
              wire_revision: int | None = None,
              catchup: bool = False) -> None:
        """Apply one upstream store event: update the cached object map
        (hash or btree storage), append to the history window, fan out.

        ``wire_revision`` (reprime's resume replay) splits the two
        roles a revision plays: the OBJECT MAP keeps the true MVCC
        ``mod_revision`` — the next reprime's diff compares against it
        — while the history window and the fanned-out event carry the
        stamped wire revision, so the resumed stream stays monotonic
        for clients whose last-seen revision is the tier's GLOBAL
        header revision (a back-dated event would be filtered by their
        re-attach ``start_revision`` and lost forever).

        ``catchup`` (replica warm restart, run_upstream's resume-floor
        path) appends to the history window WITHOUT touching the
        object map: the priming list at the prime revision is already
        the truth for objects, and replaying an old PUT into the map
        could resurrect a key the list shows deleted.  Catch-up exists
        purely so resuming clients can replay (floor, prime_rev] from
        history instead of relisting."""
        if catchup:
            pass
        elif ev_type == 0:
            existed = key in self.objects
            self.objects[key] = CachedObject(
                value, create_revision, mod_revision, version
            )
            if self.index == "btree" and not existed:
                bisect.insort(self.sorted_keys, key)
            elif self.index == "btree":
                # Existing key: the B-tree still pays the ordered-index
                # search on update — the cost the reference's experiment
                # measures (README.adoc:495-499).
                bisect.bisect_left(self.sorted_keys, key)
        else:
            if self.objects.pop(key, None) is not None and self.index == "btree":
                i = bisect.bisect_left(self.sorted_keys, key)
                if i < len(self.sorted_keys) and self.sorted_keys[i] == key:
                    del self.sorted_keys[i]
        wr = mod_revision if wire_revision is None else wire_revision
        self._seq += 1
        ev = CacheEvent(
            ev_type, key, value, create_revision, wr, version,
            seq=self._seq,
        )
        self.history.append(ev)
        self.last_revision = max(self.last_revision, wr)
        self.events_in += 1
        _EVENTS_IN.inc()
        delivered = 0
        lag = self._lag_now
        for w in self._exact.get(key, ()):
            if wr >= w.min_rev:
                w.push(ev, lag)
                delivered += 1
        for w in self._ranges:
            if wr >= w.min_rev and w.matches(key):
                w.push(ev, lag)
                delivered += 1
        self.events_out += delivered
        if delivered:
            _EVENTS_OUT.inc(delivered)

    # ---- downstream registry -------------------------------------------

    def register(
        self, key: bytes, end: bytes | None, min_rev: int = 0
    ) -> Downstream:
        # hard_cap >= lag_budget keeps the deque's maxlen a never-hit
        # backstop: an operator budget past _QUEUE_CAP must raise the
        # cancel threshold with it, or push() would stop engaging
        # coalescing and maxlen would silently evict the oldest event.
        w = Downstream(
            self._next_id, key, end, min_rev,
            hard_cap=max(_QUEUE_CAP, self.lag_budget),
        )
        self._next_id += 1
        w.owner = self
        w.last_pushed = min_rev - 1 if min_rev > 0 else self.last_revision
        if end is None:
            self._exact.setdefault(key, set()).add(w)
        else:
            self._ranges.add(w)
        _WATCHERS.inc()
        return w

    def unregister(self, w: Downstream) -> None:
        if w.end is None:
            peers = self._exact.get(w.key)
            if peers is not None:
                peers.discard(w)
                if not peers:
                    del self._exact[w.key]
        else:
            self._ranges.discard(w)
        if w.owner is self:
            # Undelivered backlog leaves the tier with the watcher.
            self._backlog -= w.backlog
            w.owner = None
        if w.coalescing:
            w.coalescing = False
            _DEGRADED_WATCHERS.dec()
        _WATCHERS.dec()

    @property
    def watcher_count(self) -> int:
        return sum(len(s) for s in self._exact.values()) + len(self._ranges)

    def replay(self, w: Downstream, start_revision: int) -> int | None:
        """Queue historical events >= start_revision for ``w``.  Returns
        the compact revision when the window no longer reaches back far
        enough (the caller sends a compact_revision cancel, mirroring
        etcd; the client relists), else None."""
        if start_revision <= 0:
            return None
        if start_revision < self.replayable_from:
            _REPLAYS.inc(outcome="compact_relist")
            return self.replayable_from
        _REPLAYS.inc(outcome="resumed")
        lag = self._lag_now
        for ev in self.history:
            if ev.mod_revision >= start_revision and w.matches(ev.key):
                w.push(ev, lag)
        return None

    # ---- cache-served Range --------------------------------------------

    def range(self, key: bytes, end: bytes, limit: int = 0):
        """Serve a list from the cache (the apiserver serves lists from
        the watch cache, which is what makes its storage structure the
        throughput-critical one).  Returns (kvs, more, count)."""
        if not end:
            obj = self.objects.get(key)
            return ([(key, obj)] if obj else [], False, 1 if obj else 0)
        if self.index == "btree":
            lo = bisect.bisect_left(self.sorted_keys, key)
            hi = (
                len(self.sorted_keys)
                if end == b"\x00"
                else bisect.bisect_left(self.sorted_keys, end)
            )
            keys = self.sorted_keys[lo:hi]
        else:
            keys = sorted(
                k for k in self.objects
                if k >= key and (end == b"\x00" or k < end)
            )
        total = len(keys)
        if limit > 0:
            keys = keys[:limit]
        return ([(k, self.objects[k]) for k in keys], total > len(keys), total)

    def stats(self) -> dict:
        return {
            "index": self.index,
            "objects": len(self.objects),
            "watchers": self.watcher_count,
            "events_in": self.events_in,
            "events_delivered": self.events_out,
            "last_revision": self.last_revision,
            "window": len(self.history),
            "backlog": self._backlog,
            "lag_budget_now": self._lag_now,
        }


async def run_upstream(
    cache: WatchCache, client: EtcdClient, prefix: bytes,
    *, primed: asyncio.Event | None = None,
    handle: "UpstreamHandle | None" = None,
    resume_floor: int = 0,
) -> None:
    """The tier's single store watch for ``prefix``: list to prime, then
    watch from the list revision, applying every event to the cache.
    Runs until cancelled; on a broken/canceled stream (or a failed
    prime — the list is retried like the stream, so a store hiccup at
    startup can't kill the task before ``primed`` fires) it relists —
    clients keep their watches, the cache absorbs the resync.

    ``handle`` tracks the live session and progress responses for the
    consistent-read gate (event-less batches on a revision-ordered
    stream are progress notifications).

    Relist pacing comes from the shared RetryPolicies (capped
    exponential backoff + jitter, effectively retrying forever — the
    tier's job is to outlive store outages), reset after every
    successful prime: ``watch.tier`` for the cold prime, the snappier
    ``watch.resume`` once primed (a resume relist races client-visible
    delivery lag, not bootstrap).  The event pump is a faultline hook
    (component ``watch.tier``, op ``upstream.recv``): an injected
    failure breaks the stream exactly like a real one — resume or
    invalidate + relist — so cache consistency under upstream loss is
    reproducible by seed.

    An upstream break does NOT cancel the clients up front: the cache
    keeps serving (the consistent-read progress gate fails while the
    stream is down, so rev=0 reads fall through to the store) while the
    relist runs, and ``reprime`` then replays the net difference to the
    live watches (``invalidate`` only when the diff overflows the
    window).

    ``resume_floor`` is the replica warm-restart knob (the fleet's
    reprime-instead-of-relist story): a relaunched replica primes at
    the current store revision as usual, then opens its upstream watch
    from ``resume_floor + 1`` and CATCH-UP applies the history in
    (floor, prime_rev] — history-window-only, no object-map writes (see
    ``WatchCache.apply``) — before signalling ``primed`` (and with it
    the serving port).  ``prime_revision`` is lowered to the floor only
    once catch-up provably completed (a post-events progress barrier at
    >= the prime revision, or any event beyond it), so ``replayable_from``
    never claims history the broken-mid-catch-up case didn't load.
    Clients of the dead replica then re-attach with their own
    ``start_revision`` and resume from the replayed window
    (``watchcache_resumes_total``) instead of relisting; if the store
    has compacted past the floor, the tier falls back to a cold prime
    and resuming clients get the honest compact-cancel."""
    end = prefix_end(prefix)
    policy = policy_for("watch.tier")
    resume_policy = policy_for("watch.resume")
    failures = 0
    primed_once = False
    warm = 0
    while True:
        try:
            # Paginated prime at a pinned revision: one unpaginated list
            # of a six-figure prefix is a single multi-MB response (the
            # 100K-watch scale run measured 6.3MB — over default client
            # message caps), exactly why every other bootstrap in this
            # framework paginates (native.list_prefix).
            page = await client.range(prefix, end, limit=_PRIME_PAGE)
            rev = page.header.revision
            kvs = list(page.kvs)
            while page.more:
                page = await client.range(
                    page.kvs[-1].key + b"\x00", end,
                    limit=_PRIME_PAGE, revision=rev,
                )
                kvs.extend(page.kvs)
            if primed_once:
                # Events were lost between the broken stream and this
                # relist; resume the clients from the snapshot diff
                # (reprime falls back to invalidate when it can't),
                # scoped to THIS stream's prefix.
                if not cache.reprime(kvs, rev, prefix, end):
                    # Fallback invalidated (clients canceled, this
                    # prefix's objects dropped); the relist in hand IS
                    # the fresh snapshot — load it, or the tier would
                    # serve an empty prefix until the next event.
                    cache.prime(kvs, rev)
            else:
                cache.prime(kvs, rev)
            primed_once = True
            failures = 0
            # Warm restart: catch up (floor, rev] from store history
            # before declaring primed; `warm` holds the prime revision
            # the catch-up must reach (0 = cold / already caught up).
            warm = rev if 0 < resume_floor < rev else 0
            if not warm:
                resume_floor = 0
                if primed is not None:
                    primed.set()
            async with client.watch(
                prefix, end,
                start_revision=(resume_floor if warm else rev) + 1,
            ) as session:
                if session.compact_revision:
                    if warm:
                        # Store compacted past the floor: the history
                        # gap is gone for good.  Fall back to a cold
                        # prime so resuming clients get the honest
                        # compact-cancel instead of a silent gap.
                        log.warning(
                            "warm restart floor %d for %r already "
                            "compacted; cold prime", resume_floor, prefix,
                        )
                        resume_floor = 0
                    continue    # relist: our revision already compacted
                if handle is not None:
                    handle.session = session
                    handle.reset_after_reprime()
                if warm:
                    # Catch-up completion probe: the store orders the
                    # progress response AFTER everything it had already
                    # queued for this watch, so a progress barrier at
                    # >= rev proves the (floor, rev] history is in.
                    # Counted as issued so a later confirm() still
                    # demands a response of its own.
                    if handle is not None:
                        handle.requests_sent += 1
                    await session.request_progress()
                try:
                    while True:
                        batch = await session.next()
                        d = faultline.decide("watch.tier", "upstream.recv")
                        if d is not None:
                            if d.kind == "delay":
                                await asyncio.sleep(d.delay_s)
                            else:
                                # Any failure kind = the upstream stream
                                # is gone.  A latest-only cache cannot
                                # "drop" a batch silently — skipping it
                                # would gap the history window — so every
                                # kind takes the honest path: invalidate,
                                # cancel the clients, relist.
                                raise InjectedFault(d)
                        if batch.canceled:
                            log.warning(
                                "upstream watch for %r canceled by store "
                                "(%s); relisting", prefix,
                                batch.cancel_reason or "no reason",
                            )
                            break   # server-side cancel -> relist
                        for ev in batch.events:
                            cache.apply(
                                1 if ev.type == mvcc_pb2.Event.DELETE else 0,
                                ev.kv.key,
                                ev.kv.value,
                                ev.kv.create_revision,
                                ev.kv.mod_revision,
                                ev.kv.version,
                                catchup=bool(
                                    warm and ev.kv.mod_revision <= warm
                                ),
                            )
                        if batch.events:
                            cache.loadshed_tick()
                        elif handle is not None:
                            handle.note_progress()
                        if warm and (
                            (not batch.events and batch.revision >= warm)
                            or (
                                batch.events
                                and batch.events[-1].kv.mod_revision > warm
                            )
                        ):
                            # Catch-up complete: history now provably
                            # covers (floor, prime_rev], so the replay
                            # window may honestly reach back to the
                            # floor — and the port may open.
                            cache.prime_revision = min(
                                cache.prime_revision, resume_floor
                            )
                            _RESUMES.inc()
                            log.info(
                                "warm restart for %r caught up: history "
                                "resumes from revision %d",
                                prefix, resume_floor + 1,
                            )
                            warm = 0
                            resume_floor = 0
                            if primed is not None:
                                primed.set()
                finally:
                    if handle is not None:
                        handle.session = None
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if warm:
                # The stream broke mid-catch-up: partial (floor, rev]
                # history is already appended, and a second catch-up
                # pass would duplicate it out of order.  Degrade to a
                # cold prime — resuming clients relist, which is the
                # honest fallback, never a silent gap.
                warm = 0
                resume_floor = 0
            failures += 1
            delay = (resume_policy if primed_once else policy).delay_for(
                failures
            )
            log.warning(
                "upstream watch for %r broke (%s); relisting in %.2fs",
                prefix, e, delay, exc_info=True,
            )
            await asyncio.sleep(delay)


class UpstreamHandle:
    """Live view of one prefix's upstream watch stream, for the
    consistent-read progress gate."""

    def __init__(self, prefix: bytes = b"") -> None:
        self.prefix = prefix
        self.session = None          # live WatchSession or None
        self.progress_count = 0      # progress responses received
        self.requests_sent = 0       # progress requests issued
        self._waiters: list[tuple[int, asyncio.Event]] = []
        # Serializes request issuance so concurrent confirms coalesce
        # onto one upstream round trip (see confirm()).
        self._confirm_gate = asyncio.Lock()

    def covers(self, key: bytes, end: bytes) -> bool:
        """True if this stream's prefix contains [key, end) (single key
        when end is empty)."""
        p = self.prefix
        if not key.startswith(p):
            return False
        if not end or end == key:
            return True
        if end == b"\x00":
            return False
        return end <= prefix_end(p)

    def note_progress(self) -> None:
        self.progress_count += 1
        still = []
        for c, e in self._waiters:
            if self.progress_count >= c:
                e.set()
            else:
                still.append((c, e))
        self._waiters = still

    def reset_after_reprime(self) -> None:
        """Stream replaced: requests in flight on the old stream will
        never be answered.  The cache was just re-primed from a fresh
        list, whose revision is at least that of any write committed
        before now — so every pending confirm's guarantee already holds;
        complete them and realign the counters."""
        self.progress_count = self.requests_sent
        for _c, e in self._waiters:
            e.set()
        self._waiters = []

    async def confirm(self, timeout: float) -> bool:
        """Request progress and wait for a response to a request issued
        at-or-after this call began; False if the stream is down/slow.

        Responses are FIFO with requests on the stream, and the store
        computes a response's barrier revision when it READS the request
        — so any response beyond the requests already issued when we
        started proves delivery through everything committed before this
        call.  Counting (not bare "a response arrived") is what stops a
        response to an EARLIER caller's request — whose barrier may
        predate our caller's write — from satisfying us.

        Concurrent confirms COALESCE (Kubernetes batches its
        requestWatchProgress calls the same way): callers queue on a
        gate; whoever holds it issues one request, and every caller that
        arrived before that issuance shares its response.  Any counter
        bump observed after a caller's arrival snapshot happened after
        its arrival, so the shared request's store-side read — which is
        later still — post-dates every write that caller must observe.
        """
        s = self.session
        if s is None:
            return False
        arrival = self.requests_sent
        async with self._confirm_gate:
            s = self.session
            if s is None:
                return False
            if self.requests_sent > arrival:
                # A request was issued after we arrived; piggyback on it.
                target = self.requests_sent
            else:
                target = self.requests_sent + 1
                self.requests_sent = target
                try:
                    await s.request_progress()
                # The swallow is the documented counter rollback below.
                except Exception:  # graftlint: disable=broad-except
                    # The request never reached the store; leaving the
                    # counter bumped would make every later confirm wait
                    # for a response that can't come (until the next
                    # reprime realigns).  BUT if the stream was replaced
                    # while we were sending, reset_after_reprime already
                    # realigned progress_count to the bumped counter —
                    # decrementing now would leave progress_count >
                    # requests_sent and let the NEXT confirm pass with no
                    # barrier from the new stream.  Only roll back when
                    # the failure wasn't a replacement.
                    if self.session is s:
                        self.requests_sent -= 1
                    # graftlint: disable=fallback-counts-or-raises (False IS the accounted signal: the caller's unconfirmed-read fallback resyncs, and that path counts via watchcache_resumes/invalidations)
                    return False
        if self.progress_count >= target:
            return True
        e = asyncio.Event()
        self._waiters.append((target, e))
        try:
            await asyncio.wait_for(e.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            # graftlint: disable=fallback-counts-or-raises (timeout -> False is the confirm() API contract; the caller owns the fallback and its accounting)
            return False


def encode_event_batch(header, watch_id: int, events) -> rpc_pb2.WatchResponse:
    """Batched proto encoding of one event frame: the whole response —
    header, watch id, and every (possibly coalesced) event — is built
    in one constructor call instead of a per-event ``events.add()`` +
    per-field assignment loop, which is measurably cheaper per frame at
    fan-out rates (shared by the tier's pump lanes and tests)."""
    return rpc_pb2.WatchResponse(
        header=header,
        watch_id=watch_id,
        events=[
            mvcc_pb2.Event(
                type=mvcc_pb2.Event.DELETE if e.type else mvcc_pb2.Event.PUT,
                kv=mvcc_pb2.KeyValue(
                    key=e.key,
                    value=e.value,
                    create_revision=e.create_revision,
                    mod_revision=e.mod_revision,
                    version=e.version,
                ),
            )
            for e in events
        ],
    )


class _PumpShard:
    """One fan-out pump lane of a Watch stream: watchers hash onto a
    lane by watch key (peers of one object share a lane, and with it a
    sweep — see ``sweep`` in Watch), and each lane services its
    ready-set sequentially.  The
    lane count bounds the task cost of a 100K-watch stream (N tasks,
    not 100K), and the bounded output queue means a wedged subscriber
    socket backpressures its own lane instead of head-of-line-blocking
    the whole tier."""

    def __init__(self) -> None:
        # Bounded by construction: each watcher latches onto the ready
        # set at most once (the _ready flag), so depth <= the lane's
        # member count.
        self.ready: collections.deque[Downstream] = collections.deque()  # graftlint: disable=bounded-watch-buffer (ready-set: the _ready latch admits each watcher at most once)
        self.event = asyncio.Event()

    def mark_ready(self, w: Downstream) -> None:
        if not w._ready:
            w._ready = True
            self.ready.append(w)
        self.event.set()


class WatchCacheService:
    """etcd wire services served from the cache tier."""

    def __init__(
        self, cache: WatchCache, upstream: EtcdClient,
        handles: list[UpstreamHandle] | None = None,
        n_pumps: int = _PUMP_SHARDS,
    ):
        self.cache = cache
        self.upstream = upstream
        self.handles = handles or []
        self.n_pumps = max(1, n_pumps)
        # Tier-level shared frame table: one encode per applied event
        # (keyed by CacheEvent.seq) no matter how many streams, lanes,
        # or watch ids fan it out.
        self.frames = wiretier.FrameTable()

    async def _confirm_progress(
        self, key: bytes, end: bytes, timeout: float = 5.0
    ) -> bool:
        """Confirm freshness for the ONE stream whose prefix covers the
        requested range (an unrelated prefix's reconnect must not force
        every read to the store); False -> serve from upstream.
        Concurrent confirms coalesce onto a shared progress round trip
        inside UpstreamHandle.confirm (as Kubernetes batches its
        requestWatchProgress calls).
        """
        for h in self.handles:
            if h.covers(key, end):
                return await h.confirm(timeout)
        return False    # range not covered by any watched prefix

    def _header(self) -> rpc_pb2.ResponseHeader:
        return self._header_at(self.cache.last_revision)

    def _header_at(self, rev: int) -> rpc_pb2.ResponseHeader:
        return rpc_pb2.ResponseHeader(
            cluster_id=1, member_id=2, revision=rev, raft_term=1,
        )

    # ---- KV.Range: served from the cache -------------------------------

    async def Range(self, req: rpc_pb2.RangeRequest, ctx) -> rpc_pb2.RangeResponse:
        if req.revision > 0:
            # A latest-only cache cannot serve an arbitrary MVCC snapshot
            # — EXCEPT when the pinned revision is exactly the cache's
            # current revision, the common case for pages 2+ of a
            # paginated list that pinned page 1's header revision on a
            # quiet prefix.  After a successful progress confirm, every
            # write committed before this read is in the cache; if
            # last_revision still equals the pin, none of those writes
            # exceeded it, so latest-state IS the state at that revision.
            # Churn (last_revision moved past the pin) falls through to
            # the store, which owns true time travel.
            if (
                req.revision == self.cache.last_revision
                and await self._confirm_progress(req.key, req.range_end)
                and req.revision == self.cache.last_revision
            ):
                return self._range_from_cache(req, req.revision)
            return await self.upstream._range(req)
        # Consistent read from cache: rev=0 on the etcd wire is
        # linearizable, so a client that just wrote through the tier must
        # see its write.  The gate is WATCH PROGRESS, exactly Kubernetes'
        # consistent-watch-cache-reads protocol (and the reason the
        # reference's store must advertise etcd >= 3.5.13,
        # maintenance_service.rs:56): request a progress notification on
        # every upstream watch stream and serve only after each stream
        # has delivered one issued AFTER this read arrived — the streams
        # are revision-ordered, so the cache then holds every write that
        # committed before the read, per watched prefix, without a
        # global-revision comparison (which a prefix-scoped cache could
        # never satisfy).  Falls through to the store if a stream is
        # reconnecting or too far behind.
        if not await self._confirm_progress(req.key, req.range_end):
            return await self.upstream._range(req)
        return self._range_from_cache(req, self.cache.last_revision)

    def _range_from_cache(
        self, req: rpc_pb2.RangeRequest, header_rev: int
    ) -> rpc_pb2.RangeResponse:
        kvs, more, count = self.cache.range(req.key, req.range_end, req.limit)
        return rpc_pb2.RangeResponse(
            header=self._header_at(header_rev),
            kvs=[
                mvcc_pb2.KeyValue(
                    key=k,
                    value=b"" if req.keys_only else o.value,
                    create_revision=o.create_revision,
                    mod_revision=o.mod_revision,
                    version=o.version,
                )
                for k, o in ([] if req.count_only else kvs)
            ],
            more=more,
            count=count,
        )

    # ---- Watch: the fan-out --------------------------------------------

    async def Watch(self, request_iterator, ctx):
        cache = self.cache
        watchers: dict[int, Downstream] = {}
        # Bounded output queue: the backpressure point for a wedged
        # subscriber socket (see _PumpShard).
        out: asyncio.Queue = asyncio.Queue(maxsize=_OUT_CAP)
        next_id = 1
        # Delivered-through revisions + barrier tasks: progress responses
        # are ordered after prior events, same contract as the store
        # server (see etcd_server.py Watch).
        cleared: dict[int, int] = {}
        barriers: set = set()
        shards = [_PumpShard() for _ in range(self.n_pumps)]

        async def cancel_watch(w: Downstream, reason: str) -> None:
            wid = w.service_id
            if watchers.get(wid) is not w:
                return      # a cancel_request already unregistered it
            cache.unregister(w)
            watchers.pop(wid, None)
            await out.put(
                rpc_pb2.WatchResponse(
                    header=self._header(),
                    watch_id=wid,
                    canceled=True,
                    cancel_reason=reason,
                )
            )

        async def sweep(shard: _PumpShard) -> None:
            """One pass over the lane's ready set: pop at most one
            batch per watcher, group watchers owing IDENTICAL batches
            (equal event-seq tuples), then compose each group's frame
            ONCE from the shared frame table and fan the bytes — the
            wiretier's shared-frame encoding.  A batch drained from a
            coalesce map is a compacted [from_rev, to_rev] window and
            says so on the wire (the shared-from extension).  Watchers
            with remainder re-latch onto the ready set, so per-watcher
            delivery order is a property of sweep ordering, not of
            grouping."""
            # group key -> [wids, events, from_rev, emptied (wid, r0)]
            groups: dict[tuple, list] = {}
            cancels: list[Downstream] = []
            for _ in range(len(shard.ready)):
                w = shard.ready.popleft()
                w._ready = False
                wid = w.service_id
                if watchers.get(wid) is not w:
                    continue    # canceled while queued
                if w.overflowed:
                    cancels.append(w)
                    continue
                r0 = cache.last_revision
                if not (w.queue or w.coalesced):
                    # Queue observed empty at r0 (snapshot taken before
                    # the check, no await between) and nothing popped
                    # earlier this sweep is pending for it (a pop
                    # re-latches or empties the queue): delivered
                    # through r0.
                    if cleared.get(wid, 0) < r0:
                        cleared[wid] = r0
                    continue
                evs = w.pop_batch(_WATCH_BATCH)
                compacted = w.last_pop_compacted
                # Subscriber-wedge fault hook: delay kinds stall this
                # lane's delivery; any failure kind means the
                # subscriber's socket is gone — cancel it (the client
                # relists, which covers the popped batch) rather than
                # let one wedged socket hold the lane.
                d = faultline.decide("watch.tier", "subscriber.send")
                if d is not None:
                    if d.kind in ("delay", "slow_cycle"):
                        await asyncio.sleep(d.delay_s)
                    else:
                        w.overflowed = True
                        cancels.append(w)
                        continue
                gk = tuple(e.seq for e in evs)
                if 0 in gk:
                    # Unstamped events (unit-test pushes) have no
                    # identity; never share their frame.
                    gk = (-wid,) + gk
                g = groups.get(gk)
                if g is None:
                    groups[gk] = g = [[wid], evs, 0, []]
                else:
                    g[0].append(wid)
                if compacted:
                    fr = cleared.get(wid, 0) + 1
                    if fr > 1 and (g[2] == 0 or fr < g[2]):
                        g[2] = fr
                if w.queue or w.coalesced:
                    shard.mark_ready(w)
                else:
                    g[3].append((wid, r0))
            # Flush: one composed frame per group.  cleared[] advances
            # only AFTER a group's frame is queued, so a progress
            # barrier can never overtake undelivered events (the
            # progress-after-events contract the consistent-read gate
            # rides).
            if groups:
                hb = wiretier.header_bytes(self._header())
                for wids, evs, from_rev, emptied in groups.values():
                    chunks = [
                        self.frames.bytes_for(e.seq, wiretier.encode_event, e)
                        for e in evs
                    ]
                    await out.put(
                        wiretier.compose_frame(
                            hb, wids, chunks, from_rev=from_rev
                        )
                    )
                    last = evs[-1].mod_revision
                    for wid in wids:
                        if cleared.get(wid, 0) < last:
                            cleared[wid] = last
                    for wid, r0 in emptied:
                        if cleared.get(wid, 0) < r0:
                            cleared[wid] = r0
            # Cancels flush after frames: a watcher popped-then-
            # overflowed this sweep must not see its cancel overtake
            # bytes already owed to its group.
            for w in cancels:
                await cancel_watch(w, "watcher overflowed; events dropped")

        async def pump_shard(shard: _PumpShard):
            try:
                while True:
                    await shard.event.wait()
                    shard.event.clear()
                    # Pump-stall fault hook: every firing kind
                    # expresses as a bounded stall of this lane — the
                    # pump never dies, it lags (and the lag shows up in
                    # the drill's delivery p99, never as loss).
                    d = faultline.decide("watch.tier", "pump.stall")
                    if d is not None:
                        await asyncio.sleep(d.delay_s or _STALL_S)
                    while shard.ready:
                        await sweep(shard)
            except asyncio.CancelledError:
                raise

        pumps = [
            asyncio.create_task(pump_shard(shard)) for shard in shards
        ]

        async def reader():
            nonlocal next_id
            async for req in request_iterator:
                which = req.WhichOneof("request_union")
                if which == "create_request":
                    cr = req.create_request
                    wid = cr.watch_id or next_id
                    next_id = max(next_id, wid) + 1
                    if wid in watchers:
                        # Reject like the store server: silently replacing
                        # would leak the old Downstream (still registered
                        # and fed) and leave its pump emitting under the
                        # same id.
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                canceled=True,
                                cancel_reason="duplicate watch_id",
                            )
                        )
                        continue
                    end = cr.range_end if cr.range_end else None
                    w = cache.register(cr.key, end, min_rev=cr.start_revision)
                    compact = cache.replay(w, cr.start_revision)
                    if compact is not None:
                        cache.unregister(w)
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                created=True,
                                canceled=True,
                                compact_revision=compact,
                            )
                        )
                        continue
                    watchers[wid] = w
                    w.service_id = wid
                    # Lanes hash on the WATCH KEY, not the id: peers
                    # watching the same object land in the same sweep,
                    # which is what lets them share one frame (their
                    # owed batches are identical whenever their lag
                    # states are).  Balance is unchanged for the many-
                    # keys population lanes exist to spread.
                    shard = shards[zlib.crc32(w.key) % len(shards)]
                    w.on_ready = shard.mark_ready
                    # Owes nothing below the registration point unless a
                    # replay queued history to deliver first.
                    if not w.backlog:
                        cleared[wid] = cache.last_revision
                    await out.put(
                        rpc_pb2.WatchResponse(
                            header=self._header(), watch_id=wid, created=True
                        )
                    )
                    if w.backlog or w.overflowed:
                        shard.mark_ready(w)
                elif which == "cancel_request":
                    wid = req.cancel_request.watch_id
                    w = watchers.pop(wid, None)
                    if w is not None:
                        cache.unregister(w)
                        await out.put(
                            rpc_pb2.WatchResponse(
                                header=self._header(),
                                watch_id=wid,
                                canceled=True,
                            )
                        )
                elif which == "progress_request":
                    rev = cache.last_revision
                    t = asyncio.create_task(
                        progress_barrier(rev, list(watchers))
                    )
                    barriers.add(t)
                    t.add_done_callback(barriers.discard)
            await out.put(None)

        async def progress_barrier(rev: int, wids: list[int]) -> None:
            while True:
                pending = [
                    wid for wid in wids
                    if wid in watchers and cleared.get(wid, 0) < rev
                ]
                if not pending:
                    break
                # Idle watchers sit off the ready sets; nudge them so an
                # event-less watch still advances its delivered-through
                # point at its pump lane.
                for wid in pending:
                    watchers[wid]._notify()
                await asyncio.sleep(0.002)
            await out.put(
                rpc_pb2.WatchResponse(
                    header=self._header_at(rev), watch_id=-1
                )
            )

        rtask = asyncio.create_task(reader())
        try:
            while True:
                resp = await out.get()
                if resp is None:
                    return
                yield resp
        finally:
            rtask.cancel()
            for task in pumps:
                task.cancel()
            for task in list(barriers):
                task.cancel()
            for w in watchers.values():
                cache.unregister(w)

    # ---- writes: proxied to the store ----------------------------------
    # The apiserver role: reads/watches served from the cache, every
    # mutation forwarded to the datastore (one connection, not one per
    # client).  With these, a client points ONLY at the tier and gets the
    # full wire — which is what lets a whole kwok/coordinator stack sit
    # behind it, the reference's apiserver-in-the-middle topology.

    async def Put(self, req: rpc_pb2.PutRequest, ctx) -> rpc_pb2.PutResponse:
        return await self.upstream._put(req)

    async def DeleteRange(
        self, req: rpc_pb2.DeleteRangeRequest, ctx
    ) -> rpc_pb2.DeleteRangeResponse:
        return await self.upstream._delete(req)

    async def Txn(self, req: rpc_pb2.TxnRequest, ctx) -> rpc_pb2.TxnResponse:
        return await self.upstream._txn(req)

    async def Compact(
        self, req: rpc_pb2.CompactionRequest, ctx
    ) -> rpc_pb2.CompactionResponse:
        return await self.upstream._compact(req)

    async def LeaseGrant(
        self, req: rpc_pb2.LeaseGrantRequest, ctx
    ) -> rpc_pb2.LeaseGrantResponse:
        return await self.upstream._lease_grant(req)

    async def LeaseRevoke(
        self, req: rpc_pb2.LeaseRevokeRequest, ctx
    ) -> rpc_pb2.LeaseRevokeResponse:
        return await self.upstream._lease_revoke(req)

    async def PutFrame(self, req, ctx):
        return await self.upstream._put_frame(req)

    async def BindFrame(self, req, ctx):
        return await self.upstream._bind_frame(req)

    # ---- Maintenance.Status --------------------------------------------

    async def Status(self, req: rpc_pb2.StatusRequest, ctx):
        return rpc_pb2.StatusResponse(
            header=self._header(), version="3.5.16", leader=1,
            raftIndex=1, raftTerm=1,
        )


@dataclasses.dataclass
class WatchCacheTier:
    """Handle to a running tier; ``close()`` tears everything down
    including the upstream channel (one watch stream per prefix)."""

    server: aio.Server
    port: int
    cache: WatchCache
    tasks: list
    upstream: EtcdClient
    svc: "WatchCacheService | None" = None

    async def close(self) -> None:
        for t in self.tasks:
            t.cancel()
        for t in self.tasks:
            try:
                await t
            # Awaiting a canceled pump; teardown continues regardless.
            except (asyncio.CancelledError, Exception):  # graftlint: disable=broad-except
                pass
        await self.upstream.close()
        await self.server.stop(None)


class _BearerAuth(aio.ServerInterceptor):
    """Reject every RPC without the expected ``authorization`` metadata.

    The closest honest analogue of the apiserver's client auth for an
    etcd-wire tier: Kubernetes clients authenticate to the apiserver
    with TLS + bearer tokens; here the tier (the apiserver stand-in)
    requires ``authorization: Bearer <token>`` on every call.
    """

    def __init__(self, token: str):
        self._expect = f"Bearer {token}"

        async def _deny_unary(request, context):
            await context.abort(
                grpc.StatusCode.UNAUTHENTICATED,
                "invalid or missing bearer token",
            )

        async def _deny_stream(request_iterator, context):
            await context.abort(
                grpc.StatusCode.UNAUTHENTICATED,
                "invalid or missing bearer token",
            )
            yield  # pragma: no cover - abort never returns

        self._deny_unary = _deny_unary
        self._deny_stream = _deny_stream

    async def intercept_service(self, continuation, details):
        md = dict(details.invocation_metadata or ())
        handler = await continuation(details)
        if (
            hmac.compare_digest(md.get("authorization", ""), self._expect)
            or handler is None
        ):
            return handler
        # Mirror the real handler's cardinality so the deny travels the
        # right stub path on the client.
        if handler.unary_unary:
            return grpc.unary_unary_rpc_method_handler(self._deny_unary)
        if handler.unary_stream:
            return grpc.unary_stream_rpc_method_handler(self._deny_stream)
        if handler.stream_unary:
            return grpc.stream_unary_rpc_method_handler(self._deny_unary)
        return grpc.stream_stream_rpc_method_handler(self._deny_stream)


async def serve_watch_cache(
    upstream_target: str,
    prefixes: list[bytes],
    port: int = 2381,
    host: str = "127.0.0.1",
    index: str = "hash",
    window: int = _DEFAULT_WINDOW,
    tls=None,
    auth_token: str | None = None,
    lag_budget: int = _LAG_BUDGET,
    pumps: int = _PUMP_SHARDS,
    resume_floor: int = 0,
) -> WatchCacheTier:
    """Start the tier: one upstream watch per prefix, etcd wire served on
    ``port``.

    ``tls`` (a cluster.certs.CertPaths) serves the wire over TLS with
    the rig chain; ``auth_token`` additionally requires a bearer token
    on every RPC — together the client-facing posture of the apiserver
    the tier stands in for (the reference's k3s serves TLS and
    authenticates clients; its plaintext side faces only mem_etcd)."""
    cache = WatchCache(index=index, window=window, lag_budget=lag_budget)
    upstream = EtcdClient(upstream_target)
    handles = [UpstreamHandle(p) for p in prefixes]
    svc = WatchCacheService(cache, upstream, handles, n_pumps=pumps)

    def _unary(fn, req_cls, resp_cls):
        return grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )

    server = aio.server(
        options=[
            ("grpc.max_concurrent_streams", 100),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ],
        interceptors=(
            (_BearerAuth(auth_token),) if auth_token is not None else ()
        ),
    )
    from k8s1m_tpu.store.proto import batch_pb2

    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler("etcdserverpb.KV", {
            "Range": _unary(svc.Range, rpc_pb2.RangeRequest, rpc_pb2.RangeResponse),
            "Put": _unary(svc.Put, rpc_pb2.PutRequest, rpc_pb2.PutResponse),
            "DeleteRange": _unary(
                svc.DeleteRange, rpc_pb2.DeleteRangeRequest,
                rpc_pb2.DeleteRangeResponse,
            ),
            "Txn": _unary(svc.Txn, rpc_pb2.TxnRequest, rpc_pb2.TxnResponse),
            "Compact": _unary(
                svc.Compact, rpc_pb2.CompactionRequest,
                rpc_pb2.CompactionResponse,
            ),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Lease", {
            "LeaseGrant": _unary(
                svc.LeaseGrant, rpc_pb2.LeaseGrantRequest,
                rpc_pb2.LeaseGrantResponse,
            ),
            "LeaseRevoke": _unary(
                svc.LeaseRevoke, rpc_pb2.LeaseRevokeRequest,
                rpc_pb2.LeaseRevokeResponse,
            ),
        }),
        grpc.method_handlers_generic_handler("k8s1m.BatchKV", {
            "PutFrame": _unary(
                svc.PutFrame, batch_pb2.PutFrameRequest,
                batch_pb2.PutFrameResponse,
            ),
            "BindFrame": _unary(
                svc.BindFrame, batch_pb2.BindFrameRequest,
                batch_pb2.BindFrameResponse,
            ),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Watch", {
            "Watch": grpc.stream_stream_rpc_method_handler(
                svc.Watch,
                request_deserializer=rpc_pb2.WatchRequest.FromString,
                # Event frames leave the pumps pre-composed (wiretier
                # shared-frame bytes); control responses stay protos.
                response_serializer=wiretier.serialize_frame_or_message,
            ),
        }),
        grpc.method_handlers_generic_handler("etcdserverpb.Maintenance", {
            "Status": _unary(svc.Status, rpc_pb2.StatusRequest, rpc_pb2.StatusResponse),
        }),
    ))
    # Prime BEFORE binding the port: a bound-but-unprimed tier would let
    # early clients read an empty cache (prime() loads objects without
    # dispatching events, so a pre-prime watcher would silently miss all
    # existing state).  Port readiness == cache readiness.
    primed_events = [asyncio.Event() for _ in prefixes]
    tasks = [
        asyncio.create_task(run_upstream(
            cache, upstream, p, primed=e, handle=h,
            resume_floor=resume_floor,
        ))
        for p, e, h in zip(prefixes, primed_events, handles)
    ]
    try:
        for e in primed_events:
            await e.wait()
        if tls is not None:
            with open(tls.key_pem, "rb") as f:
                key = f.read()
            with open(tls.cert_pem, "rb") as f:
                cert = f.read()
            creds = grpc.ssl_server_credentials([(key, cert)])
            bound = server.add_secure_port(f"{host}:{port}", creds)
        else:
            bound = server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            raise OSError(f"failed to bind {host}:{port}")
        await server.start()
    except BaseException:
        # Don't orphan the live upstream watch streams on a failed bind.
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            # Awaiting a canceled pump; teardown continues regardless.
            except (asyncio.CancelledError, Exception):  # graftlint: disable=broad-except
                pass
        await upstream.close()
        raise
    return WatchCacheTier(server, bound, cache, tasks, upstream, svc)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="watch-cache fan-out tier")
    ap.add_argument("--upstream", default="127.0.0.1:2379")
    ap.add_argument("--port", type=int, default=2381)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--prefix", action="append", default=None,
                    help="watched prefix (repeatable; default /registry/)")
    ap.add_argument("--index", choices=("hash", "btree"), default="hash",
                    help="cache storage structure (the reference's "
                         "BtreeWatchCache experiment axis)")
    ap.add_argument("--window", type=int, default=_DEFAULT_WINDOW)
    ap.add_argument("--lag-budget", type=int, default=_LAG_BUDGET,
                    help="per-subscriber FIFO depth before latest-only "
                    "coalescing engages (the loadshed controller "
                    "shrinks it under backlog)")
    ap.add_argument("--pumps", type=int, default=_PUMP_SHARDS,
                    help="fan-out pump lanes per Watch stream")
    ap.add_argument("--resume-floor", type=int, default=0,
                    help="replica warm restart: catch the history "
                    "window up from this store revision before serving "
                    "so the dead replica's clients resume from "
                    "revision instead of relisting")
    ap.add_argument("--metrics-port", type=int, default=0)
    ap.add_argument("--tls-cert", default=None,
                    help="serve TLS: path to the server cert PEM")
    ap.add_argument("--tls-key", default=None,
                    help="serve TLS: path to the server key PEM")
    ap.add_argument("--auth-token", default=None,
                    help="require 'authorization: Bearer <token>' on "
                    "every RPC (the apiserver client-auth role)")
    ap.add_argument("--fault-plan", default=None,
                    help="faultline plan: inline JSON or @path "
                    "(k8s1m_tpu/faultline; also honors K8S1M_FAULT_PLAN)")
    args = ap.parse_args(argv)
    if args.fault_plan:
        faultline.install_plan(faultline.FaultPlan.from_arg(args.fault_plan))
    prefixes = [p.encode() for p in (args.prefix or ["/registry/"])]
    tls = None
    if bool(args.tls_cert) != bool(args.tls_key):
        ap.error("--tls-cert and --tls-key must be passed together")
    if args.tls_cert:
        from k8s1m_tpu.cluster.certs import CertPaths

        tls = CertPaths(ca_pem="", cert_pem=args.tls_cert,
                        key_pem=args.tls_key)

    async def run():
        tier = await serve_watch_cache(
            args.upstream, prefixes, port=args.port, host=args.host,
            index=args.index, window=args.window,
            tls=tls, auth_token=args.auth_token,
            lag_budget=args.lag_budget, pumps=args.pumps,
            resume_floor=args.resume_floor,
        )
        if args.metrics_port:
            from k8s1m_tpu.obs.http import start_metrics_server

            start_metrics_server(args.metrics_port)
        logging.basicConfig(level=logging.INFO)
        log.info(
            "watch cache serving on :%d (upstream %s, index=%s, prefixes=%s)",
            tier.port, args.upstream, args.index,
            [p.decode() for p in prefixes],
        )
        try:
            await tier.server.wait_for_termination()
        finally:
            await tier.close()

    asyncio.run(run())


if __name__ == "__main__":
    main()
