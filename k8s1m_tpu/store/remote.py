"""Synchronous etcd-wire store client with the in-process MemStore surface.

The coordinator, KWOK controllers, and leader electors are written
against the MemStore read/write/watch API.  In a single-process rig they
share the native store directly; in a *deployed* topology (the
reference's shape: scheduler and kwok talk to the apiserver/mem_etcd over
gRPC, SURVEY.md §1) they need the same surface over the wire.  This
adapter provides it against any etcd v3 server — ours
(store/server_main.py) or a real etcd.

Watch mapping: the wire protocol has no overflow signal, so ``dropped``
is set when the stream errors or the server cancels (compaction) — the
coordinator reacts with a relist+rewatch, exactly its response to an
in-process overflow (control/coordinator.py resync), which also covers
whatever events the broken stream lost.

Resilience: every unary RPC runs under the ``store.wire`` RetryPolicy
(k8s1m_tpu/faultline/policy.py — capped exponential backoff + jitter +
deadline budget; transient gRPC errors and injected faults retry,
semantic errors like CompactedError propagate), and every call site is
a faultline injection hook (component ``store.wire``), so the client's
recovery behavior is testable by seed instead of by kill drill.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time

import grpc

from k8s1m_tpu import faultline
from k8s1m_tpu.faultline import InjectedFault, RetryPolicy, policy_for
from k8s1m_tpu.store.native import (
    CompactedError,
    FutureRevError,
    KeyValue,
    RangeResult,
    WatchEvent,
    pack_bind_frame,
    pack_put_frame,
)
from k8s1m_tpu.obs.metrics import Counter
from k8s1m_tpu.store.proto import batch_pb2, mvcc_pb2, rpc_pb2

log = logging.getLogger("k8s1m.remote_store")

_M = "etcdserverpb"

_CLIENT_COALESCED = Counter(
    "watchcache_client_coalesced_total",
    "wire-watch events elided client-side by the opt-in latest-only "
    "overflow coalescing (RemoteWatcher coalesce=True)", ()
)


def _check_unary(op: str, expressible: tuple = ()):
    """Faultline hook for a unary RPC.  ``delay`` was already applied;
    kinds in ``expressible`` are returned for the call site to apply
    (range's stale_revision, the batch writes' partial_write).  Every
    OTHER kind — ``drop``, which has no safe unary meaning short of
    silent write loss, and any kind this op cannot express — fails like
    a dropped request on the wire (the client can't tell the
    difference), so a counted injection is never a silent no-op and the
    evidence JSON never overstates coverage."""
    d = faultline.check("store.wire", op)
    if d is None or d.kind == "delay" or d.kind in expressible:
        return d
    raise InjectedFault(d)


def _kv(pb) -> KeyValue:
    return KeyValue(
        key=pb.key,
        value=pb.value,
        create_revision=pb.create_revision,
        mod_revision=pb.mod_revision,
        version=pb.version,
        lease=pb.lease,
    )


class RemoteWatcher:
    """MemStore-Watcher-shaped handle over a Watch stream.

    A dedicated reader thread drains the stream into a locked deque;
    ``poll`` is non-blocking like the native watcher's.

    ``coalesce=True`` opts into bounded-lag degradation at the client
    (the watchplane contract, mirroring the tier's per-subscriber
    coalescing): events past the FIFO cap fold latest-only-per-key
    into a bounded map instead of being dropped-and-resynced, legal for
    level-triggered consumers; only a map past ``queue_cap`` distinct
    keys starts dropping (``dropped`` goes positive, the owner
    relists).  Default off: the coordinator's drains keep the
    historical overflow->resync contract.
    """

    def __init__(
        self, store: "RemoteStore", key, end, start_revision, prev_kv,
        queue_cap: int = 0, coalesce: bool = False,
    ):
        # Client-side cap mirroring the native watcher's bounded queue: a
        # consumer that stops draining sees dropped>0 and resyncs, instead
        # of the backlog growing without bound.
        self._queue_cap = queue_cap if queue_cap > 0 else 10_000
        # maxlen is the explicit backstop; the manual cap below is the
        # working limit (overflow must COUNT, never silently evict).
        self._events: collections.deque = collections.deque(
            maxlen=self._queue_cap
        )
        self._lock = threading.Lock()
        self._coalesce = coalesce
        # key -> (etype, kv, prev): latest-only overflow regime.
        self._coalesced: dict[bytes, tuple] = {}
        self._dropped = 0
        self.canceled = False
        # Follow-mode bookkeeping (ISSUE 9): highest mod_revision this
        # stream has buffered.  A warm-standby mirror promoting over the
        # wire reads it to judge whether its drained view already covers
        # the lease-acquire revision, or whether the pinned
        # relist-from-revision diff must carry the gap.
        self.seen_revision = 0
        # The request side must stay open for the watch's lifetime — a
        # finite iterator half-closes the stream and the server cancels
        # the watch.  Requests flow through a queue; cancel() enqueues a
        # sentinel to end it.  Caller-paced (one create + one sentinel),
        # not a subscriber event buffer.
        self._requests: queue.Queue = queue.Queue()  # graftlint: disable=bounded-watch-buffer (request side: caller-paced create/cancel only)
        self._requests.put(
            rpc_pb2.WatchRequest(
                create_request=rpc_pb2.WatchCreateRequest(
                    key=key,
                    range_end=end or b"",
                    start_revision=start_revision,
                    prev_kv=prev_kv,
                )
            )
        )

        def request_iter():
            while True:
                req = self._requests.get()
                if req is None:
                    return
                yield req

        self._call = store._watch_stream(request_iter())
        self._thread = threading.Thread(
            target=self._reader, name="remote-watch", daemon=True
        )
        self._thread.start()

    def _reader(self):
        ended_clean = False
        try:
            for resp in self._call:
                d = faultline.decide("store.wire", "watch.recv")
                if d is not None:
                    if d.kind == "delay":
                        time.sleep(d.delay_s)
                    elif d.kind == "drop":
                        # This batch's events are thrown away — never
                        # silently: dropped goes positive and the owner
                        # relists, which recovers the gap.
                        self._dropped += 1
                        continue
                    else:
                        # disconnect / err5xx / stale_revision: the
                        # stream is dead from the consumer's side; same
                        # contract as a broken stream below.
                        log.warning("watch stream: injected %s", d.kind)
                        self._dropped += 1
                        ended_clean = True
                        break
                if resp.compact_revision:
                    raise CompactedError(resp.compact_revision)
                if resp.canceled:
                    if not self.canceled:
                        # Server-initiated cancel (overflow, compaction):
                        # events were lost — the owner must resync, the
                        # same contract as a native-watcher overflow.
                        log.warning(
                            "watch canceled by server: %s", resp.cancel_reason
                        )
                        self._dropped += 1
                    ended_clean = True
                    break
                if not resp.events:
                    continue
                with self._lock:
                    for ev in resp.events:
                        if ev.kv.mod_revision > self.seen_revision:
                            self.seen_revision = ev.kv.mod_revision
                        if (
                            len(self._events) >= self._queue_cap
                            or self._coalesced
                        ):
                            if not self._coalesce:
                                self._dropped += 1
                                continue
                            # Bounded-lag regime: latest-only per key
                            # (sticky until drained, so emission stays
                            # revision-ordered); past the key cap the
                            # honest drop-and-resync contract resumes.
                            key = ev.kv.key
                            if key in self._coalesced:
                                _CLIENT_COALESCED.inc()
                            elif len(self._coalesced) >= self._queue_cap:
                                self._dropped += 1
                                continue
                            self._coalesced[key] = (
                                1 if ev.type == mvcc_pb2.Event.DELETE else 0,
                                ev.kv,
                                ev.prev_kv if ev.HasField("prev_kv") else None,
                            )
                            continue
                        # Raw protobuf refs only; WatchEvent/KeyValue
                        # wrappers are built lazily in poll() so the
                        # columnar poll_pods path never pays for them.
                        self._events.append((
                            1 if ev.type == mvcc_pb2.Event.DELETE else 0,
                            ev.kv,
                            ev.prev_kv if ev.HasField("prev_kv") else None,
                        ))
        except grpc.RpcError as e:
            ended_clean = True  # error path already counted below
            if not self.canceled:
                log.warning("watch stream broke: %s", e)
                self._dropped += 1
        except CompactedError:
            ended_clean = True
            self._dropped += 1
        finally:
            if not ended_clean and not self.canceled:
                # Bare EOF: the server closed the stream without a cancel
                # response or an error (graceful shutdown).  Events after
                # this point are lost — the owner must resync, exactly as
                # for a broken stream.
                log.warning("watch stream ended by server")
                self._dropped += 1
            # Monotonic shutdown latch, raced benignly by cancel(): both
            # writers only ever set True, and the worst interleaving is a
            # second sentinel put, which the reader loop absorbs.
            self.canceled = True  # graftlint: disable=static-guarded-by (monotonic bool latch; both writers set True)
            # Unblock gRPC's request-consumer thread even when the stream
            # died server-side (cancel() will never enqueue the sentinel
            # once self.canceled is set).
            self._requests.put(None)

    def _drain_raw(self, max_events: int) -> list:
        out = []
        with self._lock:
            while self._events and len(out) < max_events:
                out.append(self._events.popleft())
            if not self._events and self._coalesced and len(out) < max_events:
                # One batched merge of the coalesced frame, revision-
                # ordered behind the FIFO (everything in the map
                # postdates everything that was queued).
                rest = sorted(
                    self._coalesced.values(),
                    key=lambda t: t[1].mod_revision,
                )
                take = rest[: max_events - len(out)]
                for t in take:
                    del self._coalesced[t[1].key]
                out.extend(take)
        return out

    def poll(self, max_events: int = 1000, timeout_ms: int = 0) -> list[WatchEvent]:
        return [
            WatchEvent(
                "DELETE" if etype else "PUT",
                _kv(kv),
                _kv(prev) if prev is not None else None,
            )
            for etype, kv, prev in self._drain_raw(max_events)
        ]

    def poll_pods(
        self, max_events: int = 10000, scheduler_name: bytes = b""
    ) -> "PodEventBatch":
        """Drain buffered wire events through the native canonical-pod
        parser (ms_parse_pod_events) — the deployed topology's version of
        the in-process watcher's poll_pods: one columnar frame instead of
        per-event Python decode (the reader buffers raw protobuf refs, so
        this path builds no per-event wrapper objects at all)."""
        from k8s1m_tpu.store.native import parse_pod_events

        return parse_pod_events(
            (
                (etype, kv.key, kv.value, kv.mod_revision)
                for etype, kv, _prev in self._drain_raw(max_events)
            ),
            scheduler_name,
        )

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._events) + len(self._coalesced)

    @property
    def dropped(self) -> int:
        return self._dropped

    def cancel(self) -> None:
        if not self.canceled:
            self.canceled = True
            self._requests.put(None)
            self._call.cancel()


class RemoteStore:
    """Blocking etcd v3 client exposing the MemStore surface."""

    def __init__(
        self,
        target: str,
        channel: grpc.Channel | None = None,
        *,
        ca_pem: str | None = None,
        token: str | None = None,
        retry_policy: RetryPolicy | None = None,
    ):
        self.target = target
        self.retry_policy = retry_policy or policy_for("store.wire")
        options = [
            # Match the servers' 64MB caps (etcd_server/watch_cache);
            # the default 4MB rejects a ~12K-object list response.
            # Large lists should still paginate (native.list_prefix)
            # — this is headroom, not an invitation.
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ]
        if channel is None and ca_pem is not None:
            from k8s1m_tpu.store.etcd_client import secure_channel_for

            channel = secure_channel_for(
                target, ca_pem, token, options=options, _aio=False
            )
        self.channel = channel or grpc.insecure_channel(
            target, options=options
        )
        c = self.channel
        pb = rpc_pb2

        def u(svc, name, req, resp):
            return c.unary_unary(
                f"/{_M}.{svc}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )

        self._range = u("KV", "Range", pb.RangeRequest, pb.RangeResponse)
        self._put = u("KV", "Put", pb.PutRequest, pb.PutResponse)
        self._delete_rpc = u(
            "KV", "DeleteRange", pb.DeleteRangeRequest, pb.DeleteRangeResponse
        )
        self._txn = u("KV", "Txn", pb.TxnRequest, pb.TxnResponse)
        self._compact_rpc = u(
            "KV", "Compact", pb.CompactionRequest, pb.CompactionResponse
        )
        self._status = u("Maintenance", "Status", pb.StatusRequest, pb.StatusResponse)
        self._watch_stream = c.stream_stream(
            f"/{_M}.Watch/Watch",
            request_serializer=pb.WatchRequest.SerializeToString,
            response_deserializer=pb.WatchResponse.FromString,
        )
        self._put_frame = c.unary_unary(
            "/k8s1m.BatchKV/PutFrame",
            request_serializer=batch_pb2.PutFrameRequest.SerializeToString,
            response_deserializer=batch_pb2.PutFrameResponse.FromString,
        )
        self._bind_frame = c.unary_unary(
            "/k8s1m.BatchKV/BindFrame",
            request_serializer=batch_pb2.BindFrameRequest.SerializeToString,
            response_deserializer=batch_pb2.BindFrameResponse.FromString,
        )

    def close(self) -> None:
        self.channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _invoke(self, op: str, fn):
        """Run one wire attempt (injection hook + RPC) under the shared
        store.wire RetryPolicy.  ``fn`` must be safe to repeat: every op
        below is a read, an idempotent put, or a CAS whose retry can only
        observe its own prior success as a conflict — the same at-least-
        once contract etcd clients live with."""
        return self.retry_policy.call(fn, op=op)

    # ---- writes --------------------------------------------------------

    def put(self, key: bytes, value: bytes, lease: int = 0) -> int:
        def once():
            _check_unary("put")
            return self._put(
                rpc_pb2.PutRequest(key=key, value=value, lease=lease)
            )
        return self._invoke("put", once).header.revision

    def delete(self, key: bytes) -> tuple[int, bool]:
        def once():
            _check_unary("delete")
            return self._delete_rpc(rpc_pb2.DeleteRangeRequest(key=key))
        resp = self._invoke("delete", once)
        if resp.deleted:
            return resp.header.revision, True
        return 0, False

    def put_batch(
        self, items: list[tuple[bytes, bytes | None]], lease: int = 0
    ) -> int:
        """A whole write wave as one BatchKV.PutFrame RPC — the wire
        equivalent of MemStore.put_batch (one FFI call server-side).
        Only works against our server; a real etcd would return
        UNIMPLEMENTED, and the caller should fall back to per-item puts."""
        def once():
            d = _check_unary("put_batch", ("partial_write",))
            if d is not None and d.kind == "partial_write" and len(items) <= 1:
                # A 1-item batch has no expressible prefix: nothing
                # lands and the connection dies — a plain wire failure.
                raise InjectedFault(d)
            if d is not None and d.kind == "partial_write":
                # The fault the WAL/crash literature actually produces: a
                # prefix of the batch lands, then the connection dies.
                # Retrying the WHOLE batch is safe — puts are idempotent
                # (the repeated prefix just bumps revisions).
                half = items[: len(items) // 2]
                self._put_frame(
                    batch_pb2.PutFrameRequest(
                        frame=pack_put_frame(half), count=len(half),
                        lease=lease,
                    )
                )
                raise InjectedFault(d)
            resp = self._put_frame(
                batch_pb2.PutFrameRequest(
                    frame=pack_put_frame(items), count=len(items), lease=lease
                )
            )
            return resp.revision
        return self._invoke("put_batch", once)

    def bind_batch(self, binds: list[tuple[bytes, int, bytes]]) -> list[int]:
        """Bind wave over one BatchKV.BindFrame RPC — the wire equivalent
        of MemStore.bind_batch (same per-record result codes)."""
        def once():
            d = _check_unary("bind_batch", ("partial_write",))
            if d is not None and d.kind == "partial_write" and len(binds) <= 1:
                raise InjectedFault(d)
            if d is not None and d.kind == "partial_write":
                # Prefix of the wave binds, then the stream dies.  The
                # retried full wave is CAS-guarded: already-bound records
                # come back as conflicts and the coordinator's conflict
                # path re-reads them (sees its own bind, drops the pod).
                half = binds[: len(binds) // 2]
                self._bind_frame(
                    batch_pb2.BindFrameRequest(
                        frame=pack_bind_frame(half), count=len(half)
                    )
                )
                raise InjectedFault(d)
            resp = self._bind_frame(
                batch_pb2.BindFrameRequest(
                    frame=pack_bind_frame(binds), count=len(binds)
                )
            )
            return list(resp.revisions)
        return self._invoke("bind_batch", once)

    def cas(
        self,
        key: bytes,
        value: bytes | None,
        *,
        required_mod: int | None = None,
        required_version: int | None = None,
        lease: int = 0,
    ) -> tuple[bool, int, KeyValue | None]:
        if (required_mod is None) == (required_version is None):
            raise ValueError("exactly one of required_mod/required_version")
        if required_mod is not None:
            cmp = rpc_pb2.Compare(
                result=rpc_pb2.Compare.EQUAL,
                target=rpc_pb2.Compare.MOD,
                key=key,
                mod_revision=required_mod,
            )
        else:
            cmp = rpc_pb2.Compare(
                result=rpc_pb2.Compare.EQUAL,
                target=rpc_pb2.Compare.VERSION,
                key=key,
                version=required_version,
            )
        op = rpc_pb2.RequestOp()
        if value is None:
            op.request_delete_range.key = key
        else:
            op.request_put.key = key
            op.request_put.value = value
            op.request_put.lease = lease
        fail = rpc_pb2.RequestOp()
        fail.request_range.key = key

        def once():
            _check_unary("txn")
            return self._txn(
                rpc_pb2.TxnRequest(compare=[cmp], success=[op], failure=[fail])
            )
        resp = self._invoke("txn", once)
        if resp.succeeded:
            return True, resp.header.revision, None
        cur = None
        for r in resp.responses:
            kvs = r.response_range.kvs
            if kvs:
                cur = _kv(kvs[0])
        return False, resp.header.revision, cur

    # ---- reads ---------------------------------------------------------

    def range(
        self,
        start: bytes,
        end: bytes | None = None,
        *,
        revision: int = 0,
        limit: int = 0,
        count_only: bool = False,
        keys_only: bool = False,
    ) -> RangeResult:
        def once():
            d = _check_unary("range", ("stale_revision",))
            if d is not None and d.kind == "stale_revision":
                # The read observes a compacted snapshot — the signal
                # consumers already recover from (list_prefix restarts
                # the pinned scan; watch owners relist).
                raise CompactedError("injected stale revision")
            try:
                return self._range(
                    rpc_pb2.RangeRequest(
                        key=start,
                        range_end=end or b"",
                        revision=revision,
                        limit=limit,
                        count_only=count_only,
                        keys_only=keys_only,
                    )
                )
            except grpc.RpcError as e:
                detail = e.details() or ""
                if "compacted" in detail:
                    raise CompactedError(detail) from None
                if "future revision" in detail or "required revision" in detail:
                    raise FutureRevError(detail) from None
                raise
        resp = self._invoke("range", once)
        return RangeResult(
            revision=resp.header.revision,
            count=resp.count,
            more=resp.more,
            kvs=[_kv(kv) for kv in resp.kvs],
        )

    def get(self, key: bytes, revision: int = 0) -> KeyValue | None:
        res = self.range(key, revision=revision)
        return res.kvs[0] if res.kvs else None

    # ---- watch ---------------------------------------------------------

    def watch(
        self,
        start: bytes,
        end: bytes | None = None,
        *,
        start_revision: int = 0,
        prev_kv: bool = False,
        queue_cap: int = 0,
        coalesce: bool = False,
    ) -> RemoteWatcher:
        """``queue_cap`` bounds the CLIENT-side buffer (default 10K like
        the native watcher): the server drains continuously into the
        stream, so overflow protection has to live where the backlog
        accumulates.  On overflow ``dropped`` goes positive and the owner
        resyncs, the same contract as a native-watcher overflow —
        unless ``coalesce=True``, which degrades to latest-only-per-key
        first (see RemoteWatcher; for level-triggered consumers)."""
        return RemoteWatcher(
            self, start, end, start_revision, prev_kv, queue_cap, coalesce
        )

    # ---- maintenance ---------------------------------------------------

    def compact(self, revision: int) -> None:
        def once():
            _check_unary("compact")
            return self._compact_rpc(
                rpc_pb2.CompactionRequest(revision=revision)
            )
        self._invoke("compact", once)

    @property
    def current_revision(self) -> int:
        def once():
            _check_unary("status")
            return self._status(rpc_pb2.StatusRequest())
        return self._invoke("status", once).header.revision
