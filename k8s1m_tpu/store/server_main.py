"""CLI bootstrap for the etcd-compatible store server.

Mirrors the reference's flags (reference mem_etcd/src/main.rs:60-81):

    python -m k8s1m_tpu.store.server_main \
        --port 2379 --metrics-port 9000 \
        --wal-dir /var/lib/memstore --wal-default buffered \
        --wal-no-write-prefix /registry/leases/
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from k8s1m_tpu.store.etcd_server import serve
from k8s1m_tpu.store.native import MemStore


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="etcd-compatible in-memory store")
    ap.add_argument("--port", type=int, default=2379)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--metrics-port", type=int, default=9000)
    ap.add_argument("--wal-dir", default=None)
    ap.add_argument(
        "--wal-default",
        choices=["none", "buffered", "fsync"],
        default="buffered",
    )
    ap.add_argument(
        "--wal-no-write-prefix",
        action="append",
        default=[],
        help="prefixes whose writes skip the WAL (e.g. /registry/leases/)",
    )
    ap.add_argument(
        "--wire",
        choices=["asyncio", "native"],
        default="native",
        help="wire implementation: the C++ front-end (native/wirefront; "
        "per-RPC path ~270x the asyncio server's) or the asyncio gRPC "
        "server",
    )
    ap.add_argument(
        "--wire-threads", type=int, default=1,
        help="event-loop threads for --wire native",
    )
    return ap.parse_args(argv)


async def amain(args):
    store = MemStore(
        wal_dir=args.wal_dir,
        wal_mode=args.wal_default,
        no_write_prefixes=tuple(args.wal_no_write_prefix),
    )
    if args.wire == "native":
        from k8s1m_tpu.store.native import WireFront

        wf = WireFront(store, host=args.host, port=args.port,
                       threads=args.wire_threads)
        if args.metrics_port:
            from k8s1m_tpu.obs.http import start_metrics_server

            start_metrics_server(args.metrics_port)
        logging.info(
            "memstore serving etcd API on :%d via native wirefront "
            "(metrics :%d)", wf.port, args.metrics_port,
        )
        # Park forever; the C++ loops do the serving.
        await asyncio.Event().wait()
        return
    server, port = await serve(
        store, port=args.port, host=args.host, metrics_port=args.metrics_port
    )
    logging.info("memstore serving etcd API on :%d (metrics :%d)", port, args.metrics_port)
    await server.wait_for_termination()


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    from k8s1m_tpu.envboot import tune_gc

    tune_gc()
    asyncio.run(amain(parse_args(argv)))


if __name__ == "__main__":
    main()
