"""Build the native memstore shared library (g++; no pip deps).

The reference ships mem_etcd as a Rust crate built by cargo
(reference mem_etcd/Cargo.toml); here the native store is C++17 compiled
on demand into the package directory.  Import-time auto-build keeps the
test suite and the driver self-contained.
"""

from __future__ import annotations

import os
import subprocess
import threading

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)), "native")
_SRC_DIRS = (
    os.path.join(_NATIVE_DIR, "memstore"),
    os.path.join(_NATIVE_DIR, "wirefront"),
)
LIB_PATH = os.path.join(_PKG_DIR, "libmemstore.so")

_lock = threading.Lock()


def _stale() -> bool:
    if not os.path.exists(LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(LIB_PATH)
    for d in _SRC_DIRS:
        for name in os.listdir(d):
            if name.endswith((".cc", ".h", ".inc")):
                if os.path.getmtime(os.path.join(d, name)) > lib_mtime:
                    return True
    return False


def ensure_built(force: bool = False) -> str:
    """Compile libmemstore.so if missing or out of date; returns its path.

    One shared object holds both the store (native/memstore) and the
    per-RPC wire front-end (native/wirefront) so the wf_* entry points
    operate on the same ms_store the ctypes bindings hold.
    """
    with _lock:
        if not force and not _stale():
            return LIB_PATH
        # Per-PID tmp: concurrent builds (many freshly spawned harness
        # subprocesses seeing a stale lib at once) must not clobber each
        # other's half-written output before the atomic replace.
        tmp = f"{LIB_PATH}.{os.getpid()}.tmp"
        cmd = [
            "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
            "-Wall", "-o", tmp,
            os.path.join(_SRC_DIRS[0], "memstore.cc"),
            os.path.join(_SRC_DIRS[1], "wirefront.cc"),
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, LIB_PATH)
        return LIB_PATH


if __name__ == "__main__":
    print(ensure_built(force=True))
