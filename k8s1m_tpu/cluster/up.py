"""Bring up a full cluster and run the standard experiment.

The single-command equivalent of the reference's terraform apply +
make_nodes + make_pods recipe (reference README.adoc:732-738):

    python -m k8s1m_tpu.cluster.up --nodes 10000 --pods 10000

Starts the native store server (etcd wire), leader+standby coordinators
and KWOK controllers over gRPC, creates the nodes, streams the pods, and
prints one JSON summary with end-to-end binds/sec.
"""

from __future__ import annotations

import argparse
import json

from k8s1m_tpu.cluster.harness import Cluster, ClusterSpec


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description="cluster bring-up + experiment")
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--pods", type=int, default=1000)
    ap.add_argument("--kwok-groups", type=int, default=2)
    ap.add_argument("--coordinators", type=int, default=2)
    ap.add_argument("--shards", type=int, default=1,
                    help=">1 deploys the scheduler shard set (pod-hash "
                    "intake split + node ownership masks + rebalancer)")
    ap.add_argument("--watch-cache", action="store_true",
                    help="deploy the apiserver tier; KWOK controllers "
                    "connect through it")
    ap.add_argument("--watch-cache-index", choices=("hash", "btree"),
                    default="hash")
    ap.add_argument("--pod-batch", type=int, default=256)
    ap.add_argument("--chunk", type=int, default=1 << 10)
    ap.add_argument("--backend", choices=("xla", "pallas"), default="xla")
    ap.add_argument("--wal-mode", choices=("none", "buffered", "fsync"),
                    default="buffered")
    ap.add_argument("--via-webhook", action="store_true",
                    help="feed pods through the admission webhook path")
    return ap.parse_args(argv)


def main(argv=None):
    from k8s1m_tpu.envboot import tune_gc

    tune_gc()
    args = parse_args(argv)
    spec = ClusterSpec(
        nodes=args.nodes,
        kwok_groups=args.kwok_groups,
        coordinators=args.coordinators,
        shards=args.shards,
        watch_cache=args.watch_cache,
        watch_cache_index=args.watch_cache_index,
        pod_batch=args.pod_batch,
        chunk=args.chunk,
        backend=args.backend,
        wal_mode=args.wal_mode,
    )
    with Cluster(spec) as cluster:
        cluster.make_nodes()
        cluster.tick(0.0)  # elect a leader, bootstrap kwok + snapshot
        stats = cluster.run_pods(args.pods, via_webhook=args.via_webhook)
        print(json.dumps(stats))


if __name__ == "__main__":
    main()
