"""KWOK-style synthetic node population, vectorized for million-node scale.

The reference creates 1M fake Node objects through the apiserver with
make_nodes (reference kwok/make_nodes/main.go:60-182: 100 clientsets x 10
workers, kwok-group pre-labeling) and lets forked-KWOK controllers maintain
their leases.  Here the equivalent "cluster" is the node table itself;
this module fills it at numpy speed (~seconds for 1M rows) with the same
shape of metadata make_nodes writes: hostname/zone/region labels, capacity
from a machine-shape mix, and optional taint groups.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from k8s1m_tpu.config import EFFECT_NO_SCHEDULE, NONE_ID, TableSpec
from k8s1m_tpu.snapshot.interning import numeric_of
from k8s1m_tpu.snapshot.node_table import (
    HOSTNAME_LABEL,
    REGION_LABEL,
    ZONE_LABEL,
    NodeTableHost,
)


@dataclasses.dataclass(frozen=True)
class KwokShape:
    """One machine shape in the synthetic fleet."""

    cpu_milli: int
    mem_kib: int
    pods: int = 110
    weight: float = 1.0


DEFAULT_SHAPES = (
    KwokShape(cpu_milli=4_000, mem_kib=16 << 20, weight=0.5),    # 4c / 16Gi
    KwokShape(cpu_milli=8_000, mem_kib=32 << 20, weight=0.3),    # 8c / 32Gi
    KwokShape(cpu_milli=16_000, mem_kib=64 << 20, weight=0.2),   # 16c / 64Gi
)


def populate_kwok_nodes(
    host: NodeTableHost,
    count: int,
    *,
    zones: int = 64,
    regions: int = 8,
    shapes: tuple[KwokShape, ...] = DEFAULT_SHAPES,
    tainted_fraction: float = 0.0,
    name_prefix: str = "kwok-node",
    seed: int = 0,
) -> np.ndarray:
    """Bulk-add ``count`` synthetic nodes; returns their rows."""
    spec = host.spec
    v = host.vocab
    rng = np.random.default_rng(seed)

    names = [f"{name_prefix}-{i}" for i in range(count)]
    rows = host.alloc_rows(names)

    # Capacity mix.
    w = np.array([s.weight for s in shapes], np.float64)
    pick = rng.choice(len(shapes), size=count, p=w / w.sum())
    host.cpu_alloc[rows] = np.array([s.cpu_milli for s in shapes], np.int32)[pick]
    host.mem_alloc[rows] = np.array([s.mem_kib for s in shapes], np.int32)[pick]
    host.pods_alloc[rows] = np.array([s.pods for s in shapes], np.int32)[pick]

    # Topology: zone round-robin, region derived (zones striped over regions).
    zone_idx = np.arange(count) % zones
    region_idx = zone_idx % regions
    zone_ids = np.array(
        [v.zones.intern(f"zone-{z}") for z in range(zones)], np.int32
    )
    region_ids = np.array(
        [v.regions.intern(f"region-{r}") for r in range(regions)], np.int32
    )
    if zone_ids.max(initial=0) >= spec.max_zones or region_ids.max(initial=0) >= spec.max_regions:
        raise ValueError("zone/region interning overflow; grow TableSpec")
    host.zone[rows] = zone_ids[zone_idx]
    host.region[rows] = region_ids[region_idx]

    # Labels: hostname, zone, region (the set make_nodes writes).
    name_ids = np.fromiter(
        (v.node_names.intern(n) for n in names), np.int32, count=count
    )
    host.name_id[rows] = name_ids
    hostname_vals = np.fromiter(
        (v.label_values.intern(n) for n in names), np.int32, count=count
    )
    zone_vals = np.array(
        [v.label_values.intern(f"zone-{z}") for z in range(zones)], np.int32
    )[zone_idx]
    region_vals = np.array(
        [v.label_values.intern(f"region-{r}") for r in range(regions)], np.int32
    )[region_idx]

    host.label_key[rows, 0] = v.label_keys.intern(HOSTNAME_LABEL)
    host.label_val[rows, 0] = hostname_vals
    host.label_key[rows, 1] = v.label_keys.intern(ZONE_LABEL)
    host.label_val[rows, 1] = zone_vals
    host.label_key[rows, 2] = v.label_keys.intern(REGION_LABEL)
    host.label_val[rows, 2] = region_vals
    host.label_num[rows, :] = numeric_of("x")  # NO_NUMERIC for all three

    # Optional taint group (e.g. dedicated nodes), mirroring make_nodes'
    # taint flags.
    if tainted_fraction > 0:
        tid = v.taints.intern(("dedicated", "special", EFFECT_NO_SCHEDULE))
        if tid >= spec.max_taint_ids:
            raise ValueError("taint interning overflow; grow TableSpec.max_taint_ids")
        tainted = rng.random(count) < tainted_fraction
        trows = rows[tainted]
        host.taint_id[trows, 0] = tid
        host.taint_effect[trows, 0] = EFFECT_NO_SCHEDULE
    return rows
