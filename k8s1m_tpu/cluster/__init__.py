from k8s1m_tpu.cluster.kwok import populate_kwok_nodes, KwokShape
from k8s1m_tpu.cluster.workload import uniform_pods

__all__ = ["populate_kwok_nodes", "KwokShape", "uniform_pods"]
