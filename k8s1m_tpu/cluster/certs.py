"""Self-signed TLS provisioning for the rig — the terraform role.

The reference provisions webhook TLS out-of-band: a terraform
``tls_self_signed_cert`` CA signs a server cert whose SANs cover the
webhook Service, and the cert/key land in ``/etc/webhook/certs`` for the
server (reference terraform/kubernetes/dist-scheduler.tf:713-740,
pkg/webhook/webhook.go:33-35); the VM metrics proxies get the same
treatment (terraform/k8s-server/server.tf:204-229).

Here the same chain is one function: ``provision(dir)`` writes a CA and
a CA-signed server cert/key and returns ready-to-use ``ssl.SSLContext``
builders for both sides.  The harness calls it when
``ClusterSpec.webhook_tls`` is set; tests use the client context to
verify the chain end to end (a client without the CA must fail).
"""

from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import os
import ssl


@dataclasses.dataclass
class CertPaths:
    ca_pem: str
    cert_pem: str
    key_pem: str

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_pem, self.key_pem)
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """Verifying client context: trusts only this rig's CA."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(self.ca_pem)
        return ctx


def provision(
    cert_dir: str,
    *,
    common_name: str = "k8s1m-webhook",
    hostnames: tuple[str, ...] = ("localhost",),
    ips: tuple[str, ...] = ("127.0.0.1",),
    days: int = 7,
) -> CertPaths:
    """Write ca.pem / cert.pem / key.pem under ``cert_dir``.

    CA-signed (not bare self-signed) so clients exercise real chain
    verification, like the reference's terraform chain.  Uses the
    ``cryptography`` package when importable, else shells out to the
    ``openssl`` CLI (same chain shape) so minimal containers can still
    run the secured-tier drills.
    """
    try:
        from cryptography import x509
    except ImportError:
        return _provision_openssl(
            cert_dir, common_name=common_name, hostnames=hostnames,
            ips=ips, days=days,
        )
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(cert_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    not_after = now + datetime.timedelta(days=days)

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "k8s1m-rig-ca")]
    )
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
        .sign(ca_key, hashes.SHA256())
    )

    key = ec.generate_private_key(ec.SECP256R1())
    san = x509.SubjectAlternativeName(
        [x509.DNSName(h) for h in hostnames]
        + [x509.IPAddress(ipaddress.ip_address(i)) for i in ips]
    )
    cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
        )
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(san, False)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), True)
        .sign(ca_key, hashes.SHA256())
    )

    paths = CertPaths(
        ca_pem=os.path.join(cert_dir, "ca.pem"),
        cert_pem=os.path.join(cert_dir, "cert.pem"),
        key_pem=os.path.join(cert_dir, "key.pem"),
    )
    with open(paths.ca_pem, "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    with open(paths.cert_pem, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(paths.key_pem, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    return paths


def _provision_openssl(
    cert_dir: str,
    *,
    common_name: str,
    hostnames: tuple[str, ...],
    ips: tuple[str, ...],
    days: int,
) -> CertPaths:
    """The same CA -> server-cert chain via the openssl CLI (P-256 keys,
    SHA-256, SANs, PKCS8 server key — byte-compatible consumers)."""
    import subprocess

    os.makedirs(cert_dir, exist_ok=True)
    paths = CertPaths(
        ca_pem=os.path.join(cert_dir, "ca.pem"),
        cert_pem=os.path.join(cert_dir, "cert.pem"),
        key_pem=os.path.join(cert_dir, "key.pem"),
    )
    ca_key = os.path.join(cert_dir, "ca_key.pem")
    raw_key = os.path.join(cert_dir, "key_ec.pem")
    csr = os.path.join(cert_dir, "csr.pem")
    ext = os.path.join(cert_dir, "ext.cnf")
    ca_cnf = os.path.join(cert_dir, "ca.cnf")
    san = ",".join(
        [f"DNS:{h}" for h in hostnames] + [f"IP:{i}" for i in ips]
    )
    with open(ext, "w") as f:
        f.write(
            f"subjectAltName={san}\n"
            "basicConstraints=critical,CA:FALSE\n"
            "subjectKeyIdentifier=hash\n"
            "authorityKeyIdentifier=keyid\n"
        )
    # Explicit config: the system default req config ALSO appends
    # basicConstraints, and a duplicated extension fails verification.
    with open(ca_cnf, "w") as f:
        f.write(
            "[req]\ndistinguished_name=dn\nx509_extensions=v3_ca\n"
            "prompt=no\n[dn]\nCN=k8s1m-rig-ca\n[v3_ca]\n"
            "basicConstraints=critical,CA:TRUE,pathlen:0\n"
            "subjectKeyIdentifier=hash\n"
        )

    def run(*cmd: str) -> None:
        subprocess.run(
            cmd, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    try:
        run("openssl", "ecparam", "-name", "prime256v1", "-genkey",
            "-noout", "-out", ca_key)
        run("openssl", "req", "-x509", "-new", "-key", ca_key,
            "-config", ca_cnf, "-days", str(days), "-sha256",
            "-out", paths.ca_pem)
        run("openssl", "ecparam", "-name", "prime256v1", "-genkey",
            "-noout", "-out", raw_key)
        run("openssl", "pkcs8", "-topk8", "-nocrypt", "-in", raw_key,
            "-out", paths.key_pem)
        run("openssl", "req", "-new", "-key", paths.key_pem,
            "-subj", f"/CN={common_name}", "-out", csr)
        run("openssl", "x509", "-req", "-in", csr, "-CA", paths.ca_pem,
            "-CAkey", ca_key, "-CAcreateserial", "-days", str(days),
            "-sha256", "-extfile", ext, "-out", paths.cert_pem)
    finally:
        # Scrub even when an openssl step fails: ca_key in particular —
        # the cryptography path keeps the CA key in memory only, and a
        # CA key left readable in cert_dir would let anything that can
        # read it mint trusted certs (the .srl serial file rides along).
        srl = os.path.splitext(paths.ca_pem)[0] + ".srl"
        for scratch in (raw_key, csr, ext, ca_cnf, ca_key, srl):
            if os.path.exists(scratch):
                os.unlink(scratch)
    return paths
