"""Self-signed TLS provisioning for the rig — the terraform role.

The reference provisions webhook TLS out-of-band: a terraform
``tls_self_signed_cert`` CA signs a server cert whose SANs cover the
webhook Service, and the cert/key land in ``/etc/webhook/certs`` for the
server (reference terraform/kubernetes/dist-scheduler.tf:713-740,
pkg/webhook/webhook.go:33-35); the VM metrics proxies get the same
treatment (terraform/k8s-server/server.tf:204-229).

Here the same chain is one function: ``provision(dir)`` writes a CA and
a CA-signed server cert/key and returns ready-to-use ``ssl.SSLContext``
builders for both sides.  The harness calls it when
``ClusterSpec.webhook_tls`` is set; tests use the client context to
verify the chain end to end (a client without the CA must fail).
"""

from __future__ import annotations

import dataclasses
import datetime
import ipaddress
import os
import ssl


@dataclasses.dataclass
class CertPaths:
    ca_pem: str
    cert_pem: str
    key_pem: str

    def server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(self.cert_pem, self.key_pem)
        return ctx

    def client_context(self) -> ssl.SSLContext:
        """Verifying client context: trusts only this rig's CA."""
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.load_verify_locations(self.ca_pem)
        return ctx


def provision(
    cert_dir: str,
    *,
    common_name: str = "k8s1m-webhook",
    hostnames: tuple[str, ...] = ("localhost",),
    ips: tuple[str, ...] = ("127.0.0.1",),
    days: int = 7,
) -> CertPaths:
    """Write ca.pem / cert.pem / key.pem under ``cert_dir``.

    CA-signed (not bare self-signed) so clients exercise real chain
    verification, like the reference's terraform chain.
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(cert_dir, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)
    not_after = now + datetime.timedelta(days=days)

    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "k8s1m-rig-ca")]
    )
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name)
        .issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
        .sign(ca_key, hashes.SHA256())
    )

    key = ec.generate_private_key(ec.SECP256R1())
    san = x509.SubjectAlternativeName(
        [x509.DNSName(h) for h in hostnames]
        + [x509.IPAddress(ipaddress.ip_address(i)) for i in ips]
    )
    cert = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
        )
        .issuer_name(ca_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(not_after)
        .add_extension(san, False)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), True)
        .sign(ca_key, hashes.SHA256())
    )

    paths = CertPaths(
        ca_pem=os.path.join(cert_dir, "ca.pem"),
        cert_pem=os.path.join(cert_dir, "cert.pem"),
        key_pem=os.path.join(cert_dir, "key.pem"),
    )
    with open(paths.ca_pem, "wb") as f:
        f.write(ca_cert.public_bytes(serialization.Encoding.PEM))
    with open(paths.cert_pem, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(paths.key_pem, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    return paths
