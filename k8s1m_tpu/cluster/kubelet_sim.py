"""Kubelet-faithful node agents: the kubelet-as-pod realism rig.

The reference validates that KWOK load is representative by also running
100K *real* kubelets in containers (k3s agents, one per pod) and
comparing control-plane load shapes (reference README.adoc:789-861,
terraform/kubelet-pod/deployment.tf).  Its finding: apiserver request
rates match, but kubelets add more watches, more Events, and more DB
size than KWOK's minimal emulation.

This module is that experiment's analogue: a ``KubeletPool`` drives
nodes with the write pattern of a real kubelet rather than KWOK's
single status patch —

- node lease renewal every 10s (same as kwok),
- periodic node status heartbeats (nodeStatusUpdateFrequency, default
  10s — a full Node object PUT, the pre-lease-era load kwok skips),
- pod lifecycle in stages: Pending -> ContainerCreating -> Running,
  one status PUT each (kwok: one),
- Events per pod: Scheduled/Pulled/Created/Started (4 PUTs into
  /registry/events/, lease-backed TTL in real clusters — the extra DB
  weight the reference measured),

so ``tools/fidelity_ab.py`` can A/B the two simulators against the same
store and report the load-shape delta the reference reports.
"""

from __future__ import annotations

import json
import zlib

from k8s1m_tpu.control.objects import lease_key, node_key, pod_key
from k8s1m_tpu.obs.metrics import Counter
from k8s1m_tpu.store.native import (
    MemStore,
    drain_events,
    list_prefix,
    prefix_end,
)

NODES_PREFIX = b"/registry/minions/"
PODS_PREFIX = b"/registry/pods/"
EVENTS_PREFIX = b"/registry/events/"
LEASE_NS = "kube-node-lease"

_WRITES = Counter(
    "kubelet_sim_writes_total", "Store writes by kind", ("kind",)
)

# Pod startup stages a kubelet reports (each is a status PUT).
_STAGES = ("ContainerCreating", "Running")
_EVENTS = ("Scheduled", "Pulled", "Created", "Started")


def event_key(namespace: str, name: str) -> bytes:
    return EVENTS_PREFIX + f"{namespace}/{name}".encode()


class KubeletPool:
    """One process's worth of simulated kubelets (the reference packs
    ~234 kubelet pods per VM; here one pool drives any node subset)."""

    def __init__(
        self,
        store: MemStore,
        *,
        lease_duration_s: int = 40,
        renew_interval_s: float = 10.0,
        status_interval_s: float = 10.0,
    ):
        self.store = store
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        self.status_interval_s = status_interval_s
        self.nodes: dict[str, bytes] = {}       # name -> last node object bytes
        self._next_renewal: dict[str, float] = {}
        self._next_status: dict[str, float] = {}
        self._pods_watch = None
        self._nodes_watch = None
        # Pods mid-startup: key -> (stage index, object dict, mod rev).
        self._starting: dict[str, tuple[int, dict, int]] = {}
        self.running_pods: set[str] = set()
        # Last observed mod revision per node — the status heartbeat is a
        # CAS against it so it can never clobber a concurrent external
        # update (e.g. a label move) made after this tick's watch drain.
        self._node_mod: dict[str, int] = {}

    def bootstrap(self, now: float = 0.0) -> None:
        kvs, rev = list_prefix(self.store, NODES_PREFIX)
        for kv in kvs:
            name = kv.key[len(NODES_PREFIX):].decode()
            self.adopt(name, kv.value, now, mod_revision=kv.mod_revision)
        self._nodes_watch = self.store.watch(
            NODES_PREFIX, prefix_end(NODES_PREFIX),
            start_revision=rev + 1, queue_cap=1 << 20,
        )
        pod_kvs, pod_rev = list_prefix(self.store, PODS_PREFIX)
        for kv in pod_kvs:
            self._observe_pod(kv.value, kv.mod_revision)
        self._pods_watch = self.store.watch(
            PODS_PREFIX, prefix_end(PODS_PREFIX),
            start_revision=pod_rev + 1, queue_cap=1 << 20,
        )

    def adopt(
        self, name: str, obj_bytes: bytes, now: float, *, mod_revision: int = 0
    ) -> None:
        self.nodes[name] = obj_bytes
        self._node_mod[name] = mod_revision
        stagger = (zlib.crc32(name.encode()) % 1000) / 1000.0
        self._next_renewal[name] = now + stagger * self.renew_interval_s
        self._next_status[name] = now + stagger * self.status_interval_s

    # ---- pod lifecycle -------------------------------------------------

    def _observe_pod(self, data: bytes, mod_revision: int) -> None:
        obj = json.loads(data)
        node = obj.get("spec", {}).get("nodeName")
        if not node or node not in self.nodes:
            return
        key = (f"{obj['metadata'].get('namespace', 'default')}/"
               f"{obj['metadata']['name']}")
        if obj.get("status", {}).get("phase") != "Pending":
            self.running_pods.add(key)
            return
        if key in self._starting or key in self.running_pods:
            return
        self._starting[key] = (0, obj, mod_revision)
        self._emit_event(obj, "Scheduled")

    def _emit_event(self, pod_obj: dict, reason: str) -> None:
        ns = pod_obj["metadata"].get("namespace", "default")
        name = pod_obj["metadata"]["name"]
        self.store.put(
            event_key(ns, f"{name}.{reason.lower()}"),
            json.dumps(
                {
                    "apiVersion": "v1",
                    "kind": "Event",
                    "metadata": {"name": f"{name}.{reason.lower()}",
                                 "namespace": ns},
                    "reason": reason,
                    "involvedObject": {"kind": "Pod", "name": name,
                                       "namespace": ns},
                },
                separators=(",", ":"),
            ).encode(),
        )
        _WRITES.inc(kind="event")

    def _advance_pod(self, key: str) -> None:
        stage, obj, mod = self._starting[key]
        ns = obj["metadata"].get("namespace", "default")
        name = obj["metadata"]["name"]
        phase = _STAGES[stage]
        status = dict(obj.get("status", {}))
        if phase == "Running":
            status["phase"] = "Running"
            status["conditions"] = [{"type": "Ready", "status": "True"}]
        else:
            status["phase"] = "Pending"
            status["reason"] = phase
        obj = {**obj, "status": status}
        ok, rev, cur = self.store.cas(
            pod_key(ns, name),
            json.dumps(obj, separators=(",", ":")).encode(),
            required_mod=mod,
        )
        _WRITES.inc(kind="pod_status")
        if ok:
            # Events only after the status write lands — a CAS retry must
            # not re-emit them (they inflate exactly the write counts the
            # fidelity A/B measures).
            if phase == "Running":
                self._emit_event(obj, "Created")
                self._emit_event(obj, "Started")
            else:
                self._emit_event(obj, "Pulled")
        if not ok:
            if cur is None:
                del self._starting[key]     # pod deleted
                return
            fresh = json.loads(cur.value)
            if fresh.get("status", {}).get("phase") == "Running":
                del self._starting[key]
                self.running_pods.add(key)
                return
            self._starting[key] = (stage, fresh, cur.mod_revision)
            return
        if phase == "Running":
            del self._starting[key]
            self.running_pods.add(key)
        else:
            self._starting[key] = (stage + 1, obj, rev)

    # ---- tick ----------------------------------------------------------

    def tick(self, now: float) -> dict:
        if self._pods_watch.dropped or self._nodes_watch.dropped:
            # Watch overflow: events were silently lost — reset ALL soft
            # state and relist (the resync contract).  Keeping nodes or
            # running_pods across the reset would resurrect deleted nodes
            # via heartbeats and skip recreated pods.
            self.close()
            self._starting.clear()
            self.nodes.clear()
            self._next_renewal.clear()
            self._next_status.clear()
            self.running_pods.clear()
            self.bootstrap(now)
        for e in drain_events(self._nodes_watch):
            name = e.kv.key[len(NODES_PREFIX):].decode()
            if e.type == "PUT":
                if name in self.nodes:
                    self.nodes[name] = e.kv.value  # track latest object
                    self._node_mod[name] = e.kv.mod_revision
                else:
                    self.adopt(
                        name, e.kv.value, now, mod_revision=e.kv.mod_revision
                    )
            else:
                # Node deleted: stop heartbeating — re-PUTting the
                # stale object would resurrect a removed node.
                self.nodes.pop(name, None)
                self._node_mod.pop(name, None)
                self._next_renewal.pop(name, None)
                self._next_status.pop(name, None)
                self.store.delete(lease_key(LEASE_NS, name))
        for e in drain_events(self._pods_watch):
            if e.type == "PUT":
                self._observe_pod(e.kv.value, e.kv.mod_revision)
            else:
                key = e.kv.key[len(PODS_PREFIX):].decode()
                self._starting.pop(key, None)
                self.running_pods.discard(key)

        renewed = statuses = 0
        for name, due in self._next_renewal.items():
            if due <= now:
                self.store.put(
                    lease_key(LEASE_NS, name),
                    json.dumps(
                        {
                            "apiVersion": "coordination.k8s.io/v1",
                            "kind": "Lease",
                            "metadata": {"name": name, "namespace": LEASE_NS},
                            "spec": {
                                "holderIdentity": name,
                                "leaseDurationSeconds": self.lease_duration_s,
                                "renewTime": now,
                            },
                        },
                        separators=(",", ":"),
                    ).encode(),
                )
                _WRITES.inc(kind="lease")
                self._next_renewal[name] = now + self.renew_interval_s
                renewed += 1
        for name, due in self._next_status.items():
            if due <= now:
                # Full Node object heartbeat — the write kwok skips.  CAS
                # on the observed revision: a conflict means an external
                # writer updated the node after our last watch drain, so
                # the heartbeat is skipped and the newer object arrives
                # via watch (like _advance_pod's rebase for pod status).
                ok, rev, cur = self.store.cas(
                    node_key(name), self.nodes[name],
                    required_mod=self._node_mod.get(name, 0),
                )
                if ok:
                    self._node_mod[name] = rev
                    _WRITES.inc(kind="node_status")
                    statuses += 1
                else:
                    # Rebase from the conflicting KV the CAS already
                    # returned, so the next heartbeat carries the
                    # external change (no extra read round trip).
                    _WRITES.inc(kind="node_status_conflict")
                    if cur is not None:
                        self.nodes[name] = cur.value
                        self._node_mod[name] = cur.mod_revision
                self._next_status[name] = now + self.status_interval_s

        # Advance every mid-startup pod one stage per tick.
        for key in list(self._starting):
            self._advance_pod(key)

        return {
            "renewed": renewed,
            "node_statuses": statuses,
            "starting": len(self._starting),
            "running": len(self.running_pods),
        }

    def close(self) -> None:
        for w in (self._pods_watch, self._nodes_watch):
            if w is not None:
                w.cancel()
        self._pods_watch = self._nodes_watch = None
