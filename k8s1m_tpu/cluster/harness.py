"""Cluster bring-up harness: the terraform/RUNNING-equivalent rig.

The reference deploys its control plane with ~3,100 lines of terraform
(mem_etcd systemd unit, k3s servers, dist-scheduler Deployments, kwok
StatefulSet, load-gen VMs — reference SURVEY.md §2.4); the experiment
recipe is a tfvars file per cluster shape.  Here the same topology is a
declarative ``ClusterSpec`` and one supervisor:

- the native store runs as a real subprocess serving the etcd v3 wire
  (store/server_main.py), WAL modes and no-write prefixes configured the
  way the reference's systemd unit passes --wal-default /
  --wal-no-write-prefix (etcd.tf:1-38);
- ``coordinators`` HACoordinator replicas (leader + standbys) and
  ``kwok_groups`` KWOK controllers connect over gRPC via RemoteStore —
  every component crosses a process boundary exactly as deployed;
- the webhook intake server fronts the current leader.

``tick(now)`` advances the whole cluster one step (tick-driven like the
KWOK simulator, so integration tests control time); ``run_pods`` is the
make_pods + wait-for-binds experiment loop (reference README.adoc:732-738).
"""

from __future__ import annotations

import atexit
import dataclasses
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

from k8s1m_tpu.cluster.kwok_controller import KwokController
from k8s1m_tpu.config import PodSpec, TableSpec
from k8s1m_tpu.control.coordinator import Coordinator
from k8s1m_tpu.control.leader import HACoordinator, LeaderElector
from k8s1m_tpu.control.objects import encode_node, encode_pod, node_key, pod_key
from k8s1m_tpu.control.webhook import WebhookServer
from k8s1m_tpu.plugins.registry import Profile
from k8s1m_tpu.snapshot.pod_encoding import PodInfo
from k8s1m_tpu.store.remote import RemoteStore
from k8s1m_tpu.tools.make_nodes import build_node


@dataclasses.dataclass
class ClusterSpec:
    """One cluster shape — the tfvars equivalent."""

    nodes: int = 1000
    kwok_groups: int = 2
    coordinators: int = 2          # leader + standbys
    # >1 switches the control plane to a scheduler shard set
    # (control/shardset.py): N cooperating coordinators splitting the pod
    # stream by FNV hash and the node space by ownership masks, with a
    # lease-elected rebalancer — the reference's 256-replica scale-out
    # topology (schedulerset.go, leader_activities.go).  ``coordinators``
    # is ignored in shard mode.
    shards: int = 1
    # Minimum simulated seconds between rebalance rounds (the reference's
    # 30 s floor, leader_activities.go).
    rebalance_interval_s: float = 30.0
    zones: int = 8
    regions: int = 4
    # When set, every subprocess's stderr is shipped into ONE
    # timestamped JSONL under this directory (obs/logship.py — the
    # fluent-bit role at rig scale, reference terraform/kubernetes/
    # fluentbit.tf).  None = inherit stderr (test-friendly default).
    log_dir: str | None = None
    wal_mode: str = "buffered"
    # The reference skips the WAL for the lease-flood prefix
    # (--wal-no-write-prefix; leases are 100K writes/s of pure churn).
    no_write_prefixes: tuple[str, ...] = ("/registry/leases/",)
    # Periodic MVCC compaction, the apiserver's --etcd-compaction-interval
    # (the reference tunes it to 20m, server.tf:28-39; simulated seconds).
    compact_interval_s: float = 1200.0
    # Deploy the watch-cache fan-out tier (store/watch_cache.py) between
    # the store and the node-simulation consumers: KWOK controllers —
    # the stand-ins for the reference's kubelets, whose 18M watches hit
    # the apiserver's watch cache and never reach etcd
    # (README.adoc:410-416) — connect to the tier; writes proxy through.
    watch_cache: bool = False
    watch_cache_index: str = "hash"
    # Tier replica count: N watch-cache processes over the ONE store,
    # consumers assigned round-robin — the reference's 11-apiserver
    # fleet behind haproxy SRV round-robin (reference
    # README.adoc:721-723, terraform/k8s-server/server.tf:230-251).
    tier_replicas: int = 1
    # Serve the webhook intake over HTTPS with rig-provisioned certs
    # (cluster/certs.py — the reference's terraform-provisioned webhook
    # TLS, dist-scheduler.tf:713-740, webhook.go:33-35).
    webhook_tls: bool = False
    # Secure the watch-cache tier like the apiserver it stands in for:
    # rig-CA TLS + bearer-token auth on every RPC; the KWOK/kubelet
    # consumers behind the tier connect with the CA + token.  Requires
    # watch_cache=True.
    tier_tls: bool = False
    # Deterministic fault injection (k8s1m_tpu/faultline): a FaultPlan
    # (or its JSON/dict form) installed process-wide for the in-process
    # components (coordinators, shard members, RemoteStore clients) and
    # inherited by the tier subprocesses via K8S1M_FAULT_PLAN — the
    # tfvars-level switch that turns a cluster shape into a drill.
    fault_plan: "object | None" = None
    table: TableSpec | None = None
    pod_batch: int = 256
    profile: Profile = dataclasses.field(
        default_factory=lambda: Profile(topology_spread=0, interpod_affinity=0)
    )
    chunk: int = 1 << 10
    backend: str = "xla"
    # Device-mesh execution (parallel/mesh.py): "DPxSP", "auto", or None
    # (None defers to K8S1M_MESH; unset = single-device).  The mesh and
    # the scheduler shard set are different scale-out axes — shard mode
    # pins its members single-device (compose meshes across processes).
    mesh: str | None = None

    def __post_init__(self):
        # Fail before any subprocess is spawned: a bad value raised from
        # Cluster.__init__ after the store Popen would leak the server
        # until interpreter exit.
        if self.watch_cache_index not in ("hash", "btree"):
            raise ValueError(
                f"watch_cache_index must be hash|btree, "
                f"got {self.watch_cache_index!r}"
            )
        if self.tier_tls and not self.watch_cache:
            raise ValueError("tier_tls requires watch_cache=True")
        if self.tier_replicas < 1:
            raise ValueError("tier_replicas must be >= 1")
        if self.tier_replicas > 1 and not self.watch_cache:
            raise ValueError("tier_replicas > 1 requires watch_cache=True")
        if self.mesh and self.shards > 1:
            raise ValueError(
                "mesh and shards > 1 are different scale-out axes; "
                "compose them across processes, not inside one spec"
            )

    def table_spec(self) -> TableSpec:
        if self.table is not None:
            return self.table
        cap = 1 << max(6, (self.nodes - 1).bit_length())
        return TableSpec(
            max_nodes=cap,
            max_zones=max(16, self.zones + 1),
            max_regions=max(8, self.regions + 1),
        )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_for_port(
    port: int, timeout_s: float = 30.0,
    proc: subprocess.Popen | None = None,
) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            raise RuntimeError(
                f"server for :{port} exited rc={proc.returncode} "
                "before listening"
            )
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1.0):
                return
        except OSError:
            # Deadline-bounded port-readiness poll, not an op retry.
            time.sleep(0.05)  # graftlint: disable=retry-through-policy
    raise TimeoutError(f"store server did not listen on :{port}")


class Cluster:
    """Running instance of a ClusterSpec."""

    def __init__(self, spec: ClusterSpec, *, wal_dir: str | None = None):
        self.spec = spec
        self.wal_dir = wal_dir or tempfile.mkdtemp(prefix="k8s1m-wal-")
        # Fault plan: installed for in-process components, exported to
        # every subprocess this harness spawns (tier replicas read it at
        # their first injection hook).
        self.fault_plan = None
        self._sub_env = None
        if spec.fault_plan is not None:
            from k8s1m_tpu.faultline import FaultPlan, install_plan

            fp = spec.fault_plan
            if not isinstance(fp, FaultPlan):
                fp = FaultPlan.from_json(fp)
            self.fault_plan = fp
            install_plan(fp)
            self._sub_env = {
                **os.environ, "K8S1M_FAULT_PLAN": fp.to_json()
            }
        # Everything shutdown() touches exists before anything can fail,
        # so a partial-init crash still tears the subprocess down cleanly
        # at exit.
        self._server = None
        self.log_shipper = None
        if spec.log_dir:
            from k8s1m_tpu.obs.logship import LogShipper

            self.log_shipper = LogShipper(spec.log_dir)
            self.log_shipper.attach_logging()
        self._clients: list[RemoteStore] = []
        self.coordinators: list[HACoordinator] = []
        self.kwoks: list[KwokController] = []
        self.webhook: WebhookServer | None = None
        self.port = _free_port()
        cmd = [
            sys.executable, "-m", "k8s1m_tpu.store.server_main",
            "--host", "127.0.0.1", "--port", str(self.port),
            "--metrics-port", "0",
            "--wal-dir", self.wal_dir, "--wal-default", spec.wal_mode,
        ]
        for p in spec.no_write_prefixes:
            cmd += ["--wal-no-write-prefix", p]
        self._server = subprocess.Popen(
            cmd, stderr=self._ship("store"), env=self._sub_env
        )
        self._tier = None
        self.tier_port: int | None = None
        atexit.register(self.shutdown)
        wait_for_port(self.port, proc=self._server)

        # Rig TLS chain, shared by whichever endpoints are secured
        # (webhook https intake, tier wire) — the terraform-provisioned
        # cert chain role (cluster/certs.py).
        self.certs = None
        self.tier_token: str | None = None
        if spec.webhook_tls or spec.tier_tls:
            from k8s1m_tpu.cluster.certs import provision

            self.certs = provision(f"{self.wal_dir}/certs")

        self._tiers: list = []
        self.tier_ports: list[int] = []
        self._tier_rr = 0
        if spec.watch_cache:
            if spec.tier_tls:
                import secrets

                self.tier_token = secrets.token_hex(16)
            for i in range(spec.tier_replicas):
                port = _free_port()
                tier_cmd = [
                    sys.executable, "-m", "k8s1m_tpu.store.watch_cache",
                    "--upstream", f"127.0.0.1:{self.port}",
                    "--host", "127.0.0.1", "--port", str(port),
                    "--prefix", "/registry/",
                    "--index", spec.watch_cache_index,
                ]
                if spec.tier_tls:
                    tier_cmd += [
                        "--tls-cert", self.certs.cert_pem,
                        "--tls-key", self.certs.key_pem,
                        "--auth-token", self.tier_token,
                    ]
                self._tiers.append(subprocess.Popen(
                    tier_cmd, stderr=self._ship(f"tier-{i}"),
                    env=self._sub_env,
                ))
                self.tier_ports.append(port)
            self._tier = self._tiers[0]
            self.tier_port = self.tier_ports[0]
            # Port bind happens after cache priming (watch_cache.py), so
            # this doubles as the primed signal.  Priming walks the whole
            # store, so the wait must scale with it (1M nodes would blow
            # the default 30s).
            prime_timeout = 30.0 + spec.nodes / 5000.0
            for proc, port in zip(self._tiers, self.tier_ports):
                wait_for_port(port, timeout_s=prime_timeout, proc=proc)

        self.shard_members: list = []
        self._rebalancer = None
        self._reb_elector = None
        if spec.shards > 1:
            from k8s1m_tpu.control.shardset import Rebalancer, ShardMember

            for i in range(spec.shards):
                store = self._client()
                coord = Coordinator(
                    store, spec.table_spec(), PodSpec(batch=spec.pod_batch),
                    spec.profile, chunk=spec.chunk, backend=spec.backend,
                    with_constraints=spec.profile.topology_spread > 0
                    or spec.profile.interpod_affinity > 0,
                    # Shard members scale out by row masks; "none" also
                    # shuts out a K8S1M_MESH inherited from the rig env.
                    mesh="none",
                )
                self.shard_members.append(
                    ShardMember(store, coord, i, spec.shards)
                )
            for m in self.shard_members:
                m.start(now=0.0)
            # The rebalancer runs wherever the control-plane lease lands
            # (any member's host view works — they all track every node).
            self._reb_elector = LeaderElector(
                self._client(), "rebalancer", name="shardset-rebalancer"
            )
            self._rebalancer = Rebalancer(
                self._clients[0], self.shard_members[0].coordinator.host,
                spec.shards, min_interval=spec.rebalance_interval_s,
            )
        else:
            for i in range(spec.coordinators):
                store = self._client()
                self.coordinators.append(
                    HACoordinator(
                        LeaderElector(store, f"coordinator-{i}"),
                        lambda store=store: Coordinator(
                            store, spec.table_spec(),
                            PodSpec(batch=spec.pod_batch),
                            spec.profile, chunk=spec.chunk,
                            backend=spec.backend,
                            with_constraints=spec.profile.topology_spread > 0
                            or spec.profile.interpod_affinity > 0,
                            # spec.mesh ("DPxSP"/"auto"/None->K8S1M_MESH)
                            # is the tfvars-level production-mesh switch.
                            mesh=spec.mesh,
                        ),
                    )
                )
        self.kwoks = [
            KwokController(self._kwok_client(), group=g)
            for g in range(spec.kwok_groups)
        ]
        ssl_context = (
            self.certs.server_context() if spec.webhook_tls else None
        )
        self.webhook = WebhookServer(
            self._webhook_sink, ssl_context=ssl_context
        ).start()
        self._kwok_bootstrapped = False
        self.now = 0.0  # simulated time, monotonic across run_pods calls
        self._next_compact = spec.compact_interval_s
        self._compact_target = 0

    # ---- plumbing ------------------------------------------------------

    def _client(
        self, port: int | None = None, *, secure: bool = False
    ) -> RemoteStore:
        c = RemoteStore(
            f"127.0.0.1:{port if port is not None else self.port}",
            ca_pem=self.certs.ca_pem if secure else None,
            token=self.tier_token if secure else None,
        )
        self._clients.append(c)
        return c

    def _kwok_client(self) -> RemoteStore:
        """Node-simulation consumers connect through the watch-cache tier
        when deployed (the kubelet→apiserver edge); else to the store.
        With ``tier_tls`` they authenticate like kubelets to an
        apiserver: rig-CA TLS + bearer token.  With ``tier_replicas`` > 1
        consumers are assigned round-robin over the LIVE replicas (the
        haproxy SRV round-robin role; a killed replica is skipped the
        way haproxy pulls a dead backend)."""
        port = self.tier_port
        if len(self.tier_ports) > 1:
            for _ in range(len(self.tier_ports)):
                i = self._tier_rr % len(self.tier_ports)
                self._tier_rr += 1
                if self._tiers[i].poll() is None:
                    port = self.tier_ports[i]
                    break
        return self._client(port, secure=self.spec.tier_tls)

    def kill_tier_replica(self, i: int) -> None:
        """Crash drill: SIGKILL tier replica ``i``.  Consumers connected
        to it lose their watches (stream reset -> resync, the same
        contract as a store watch cancel); new consumers round-robin
        over the survivors."""
        self._tiers[i].kill()
        self._tiers[i].wait()

    def _webhook_sink(self, obj: dict) -> None:
        if self.shard_members:
            # Route by the same FNV pod hash the members' intake filters
            # use (the reference webhook resolves GetTargetForScoring the
            # same way, schedulerset.go:130-143).
            from k8s1m_tpu.control.shardset import pod_shard

            meta = obj.get("metadata", {})
            key = f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
            idx = pod_shard(key, len(self.shard_members))
            self.shard_members[idx].coordinator.submit_external(obj)
            return
        for ha in self.coordinators:
            if ha.elector.is_leader:
                ha.submit_external(obj)
                return

    @property
    def leader(self) -> HACoordinator | None:
        for ha in self.coordinators:
            if ha.elector.is_leader:
                return ha
        return None

    # ---- lifecycle -----------------------------------------------------

    def make_nodes(self, count: int | None = None) -> None:
        """Bulk-create KWOK nodes (make_nodes equivalent, in-harness)."""
        store = self._clients[0]
        n = count if count is not None else self.spec.nodes
        for i in range(n):
            node = build_node(
                i, zones=self.spec.zones, regions=self.spec.regions
            )
            node.labels["kwok-group"] = str(i % self.spec.kwok_groups)
            store.put(node_key(node.name), encode_node(node))

    def tick(self, now: float | None = None) -> dict:
        """Advance every component one step.  ``now=None`` advances the
        cluster's simulated clock by one second; an explicit ``now`` only
        moves it forward (time never rewinds across run_pods calls)."""
        self.now = self.now + 1.0 if now is None else max(self.now, now)
        now = self.now
        if not self._kwok_bootstrapped:
            for k in self.kwoks:
                k.bootstrap(now)
            self._kwok_bootstrapped = True
        bound = sum(ha.tick(now) for ha in self.coordinators)
        bound += sum(m.tick(now) for m in self.shard_members)
        if self._rebalancer is not None and self._reb_elector.tick(now):
            self._rebalancer.run_once(now)
        kwok = [k.tick(now) for k in self.kwoks]
        if now >= self._next_compact:
            # Windowed compaction like the apiserver's: compact away
            # history older than one full interval.
            self._next_compact = now + self.spec.compact_interval_s
            current = self._clients[0].current_revision
            target, self._compact_target = self._compact_target, current
            if 1 < target <= current:
                self._clients[0].compact(target)
        return {
            "bound": bound,
            "leases_renewed": sum(s["renewed"] for s in kwok),
            "pods_started": sum(s["started"] for s in kwok),
        }

    _run_seq = 0

    def run_pods(
        self,
        count: int,
        *,
        max_ticks: int = 1000,
        tick_s: float = 1.0,
        via_webhook: bool = False,
        prefix: str | None = None,
    ) -> dict:
        """The make_pods experiment: create pods, tick until all bound and
        Running; returns timing/throughput stats (wall-clock based — this
        is the measurement loop, not the simulator).  Pod names get a
        per-run prefix: pod names are unique for the object's lifetime in
        Kubernetes, so runs must not reuse live names."""
        if prefix is None:
            Cluster._run_seq += 1
            prefix = f"bench{Cluster._run_seq}"
        store = self._clients[0]
        # Invariant across the loop; building it per request would charge
        # N cert parses to the measured window.
        tls_ctx = (
            self.certs.client_context() if self.spec.webhook_tls else None
        )
        t0 = time.perf_counter()
        for i in range(count):
            pod = encode_pod(
                PodInfo(f"{prefix}-{i}", cpu_milli=100, mem_kib=200 << 10)
            )
            if via_webhook:
                # Over real HTTP — the admission path under test is the
                # WebhookServer, not its sink function.
                review = {
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "request": {"uid": f"{prefix}-{i}", "object": json.loads(pod)},
                }
                # Chain-verified when TLS is on: the client trusts only
                # the rig CA and checks the cert's 127.0.0.1 IP SAN.
                scheme = "https" if tls_ctx is not None else "http"
                req = urllib.request.Request(
                    f"{scheme}://127.0.0.1:{self.webhook.port}/validate",
                    data=json.dumps(review).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with urllib.request.urlopen(
                    req, timeout=10, context=tls_ctx
                ) as resp:
                    assert json.loads(resp.read())["response"]["allowed"]
            store.put(pod_key("default", f"{prefix}-{i}"), pod)
        created_s = time.perf_counter() - t0

        bound = started = 0
        for _ in range(max_ticks):
            stats = self.tick(self.now + tick_s)
            bound += stats["bound"]
            started += stats["pods_started"]
            if bound >= count and started >= count:
                break
        total_s = time.perf_counter() - t0
        return {
            "pods": count,
            "prefix": prefix,
            "created_s": round(created_s, 3),
            "bound": bound,
            "running": started,
            "total_s": round(total_s, 3),
            "binds_per_sec": round(bound / total_s, 1),
        }

    def _ship(self, src: str):
        """stderr target for a subprocess: the log shipper's pipe when
        aggregation is on, else inherit."""
        return self.log_shipper.pipe(src) if self.log_shipper else None

    def _stop_server(self) -> None:
        self._server.terminate()
        try:
            self._server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._server.kill()
            self._server.wait()

    def restart_store(self) -> None:
        """Kill and restart the store server on the same port + WAL dir —
        the crash-recovery drill: WAL replay restores state, broken watch
        streams surface as dropped and every consumer relists."""
        cmd = self._server.args
        self._stop_server()
        self._server = subprocess.Popen(
            cmd, stderr=self._ship("store"), env=self._sub_env
        )
        # WAL-skipped prefixes (leases) lower the replayed revision below
        # the pre-crash counter; a stale compaction target would then be
        # a future revision the store rejects.
        self._compact_target = 0
        wait_for_port(self.port)
        # Wait until every live watch stream has observed the break —
        # gRPC delivers it asynchronously (~100ms), while simulated ticks
        # can outrun wall time; a real cluster ticks in wall time, so the
        # drill should too.
        deadline = time.monotonic() + 5.0
        watchers = []
        for k in self.kwoks:
            watchers += [k._nodes_watch, k._pods_watch]
        for ha in self.coordinators:
            if ha.coord is not None:
                watchers += [ha.coord._nodes_watch, ha.coord._pods_watch]
        for w in watchers:
            while (
                w is not None and not w.canceled and not w.dropped
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)

    def shutdown(self) -> None:
        if self._server is None:
            return
        if self.fault_plan is not None:
            # The injector is process-global: without this reset the
            # faulted cluster's plan would keep firing into whatever
            # cluster (or test) runs next in this process.
            from k8s1m_tpu.faultline import install_plan

            install_plan(None)
        if self.webhook is not None:
            self.webhook.stop()
        for ha in self.coordinators:
            ha.stop()
        for m in self.shard_members:
            try:
                m.close()
            # Teardown ladder: one member's close must not strand the rest.
            except Exception:  # graftlint: disable=broad-except
                pass
        for k in self.kwoks:
            k.close()
        for c in self._clients:
            try:
                c.close()
            # Teardown ladder: one client's close must not strand the rest.
            except Exception:  # graftlint: disable=broad-except
                pass
        for tier in self._tiers:
            tier.terminate()
        for tier in self._tiers:
            try:
                tier.wait(timeout=10)
            except subprocess.TimeoutExpired:
                tier.kill()
                tier.wait()
        self._tiers = []
        self._tier = None
        self._stop_server()
        self._server = None
        if self.log_shipper is not None:
            # After the subprocesses exit: pipe readers only see EOF once
            # the last holder of the write fd is gone, so closing earlier
            # burns the join timeout and drops the store's final stderr
            # lines — the shutdown errors the shipper exists to capture.
            self.log_shipper.close()
            self.log_shipper = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
