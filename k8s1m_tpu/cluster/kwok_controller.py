"""KWOK-style node simulator: fake kubelets at cluster scale.

The reference runs a forked KWOK v0.6.0 as a StatefulSet of 10
controllers, each adopting nodes with its ``kwok-group=<ordinal>`` label,
maintaining 40s node leases and driving pod status
(reference kwok/kwok-controller.yaml:9,53,58; SURVEY.md §2.8).  This is
the same component against our store: each controller owns a node group,
renews the group's leases, and moves pods bound to its nodes from
Pending to Running.

Tick-driven (no wall-clock sleeps): the caller advances simulated time,
so tests and the bench can run lease churn at any speed.  The fork's
``kwok_node_lease_delay_percentile`` metric (dashboard.json:7069) is
reproduced as a histogram of (actual - scheduled) renewal delay.
"""

from __future__ import annotations

import json
import logging
import zlib

from k8s1m_tpu.control.objects import lease_key, pod_key
from k8s1m_tpu.obs.metrics import Counter, Histogram
from k8s1m_tpu.store.native import (
    MemStore,
    drain_events,
    list_prefix,
    prefix_end,
)

log = logging.getLogger("k8s1m.kwok")

NODES_PREFIX = b"/registry/minions/"
PODS_PREFIX = b"/registry/pods/"
# Cap on pods parked waiting for an unseen node, and how long a node may
# stay unseen before its parked pods become evictable (see tick()).
MAX_WAITING_PODS = 10_000
WAITING_GRACE_S = 60.0
LEASE_NS = "kube-node-lease"

_LEASE_RENEWALS = Counter(
    "kwok_lease_renewals_total", "Node lease renewals", ("group",)
)
_PODS_STARTED = Counter(
    "kwok_pods_started_total", "Pods moved to Running", ("group",)
)
_LEASE_DELAY = Histogram(
    "kwok_node_lease_delay_seconds",
    "Delay between scheduled and actual lease renewal",
    ("group",),
)


class KwokController:
    """One controller instance owning one kwok-group of nodes."""

    def __init__(
        self,
        store: MemStore,
        group: int = 0,
        *,
        lease_duration_s: int = 40,
        renew_interval_s: float = 10.0,
    ):
        self.store = store
        self.group = str(group)
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        self.nodes: set[str] = set()
        self._next_renewal: dict[str, float] = {}
        self._nodes_watch = None
        self._pods_watch = None
        self.running_pods: set[str] = set()
        self._started_total = 0   # monotonic; survives resync resets
        # Pods bound to one of OUR nodes whose adoption event hasn't been
        # applied yet (node and pod watches are separate queues, so a bind
        # can be seen before its node) — parked per node, started on adopt.
        self._waiting: dict[str, dict[str, tuple[bytes, int]]] = {}
        # First tick time each unseen node started parking pods.
        self._waiting_since: dict[str, float] = {}
        # Nodes known to belong to other groups.  The controller already
        # lists+watches ALL nodes (it must, to discover label moves), so
        # ownership is answered locally instead of with a store round trip
        # per bound-pod event (which over gRPC would be (groups-1) x pods
        # extra blocking RPCs on the bind hot path).  ~60 bytes/node of
        # interned strings — the same order as the reference controller's
        # own node cache.
        self._foreign: set[str] = set()

    # ---- membership ----------------------------------------------------

    def _owns(self, node_obj: dict) -> bool:
        labels = node_obj.get("metadata", {}).get("labels", {})
        return labels.get("kwok-group") == self.group

    def bootstrap(self, now: float = 0.0) -> None:
        # Paginated list+watch (native.list_prefix): an unpaginated 1M-node
        # list is one ~350MB response — over any wire it must chunk.
        kvs, rev = list_prefix(self.store, NODES_PREFIX)
        for kv in kvs:
            obj = json.loads(kv.value)
            if self._owns(obj):
                self._adopt(obj["metadata"]["name"], now)
            else:
                self._foreign.add(obj["metadata"]["name"])
        self._nodes_watch = self.store.watch(
            NODES_PREFIX, prefix_end(NODES_PREFIX),
            start_revision=rev + 1, queue_cap=1 << 20,
        )
        pod_kvs, pod_rev = list_prefix(self.store, PODS_PREFIX)
        for kv in pod_kvs:
            self._maybe_start_pod(kv.value, kv.mod_revision, now)
        self._pods_watch = self.store.watch(
            PODS_PREFIX, prefix_end(PODS_PREFIX),
            start_revision=pod_rev + 1, queue_cap=1 << 20,
        )

    def _adopt(self, name: str, now: float) -> None:
        self.nodes.add(name)
        # Stagger first renewals across the interval so 1M leases spread
        # evenly instead of arriving in one spike.  crc32, not hash():
        # hash() is salted per process, which would make the renewal
        # schedule (and the delay histogram) nondeterministic across runs.
        offset = (zlib.crc32(name.encode()) % 1000) / 1000.0 * self.renew_interval_s
        self._next_renewal[name] = now + offset
        self._foreign.discard(name)
        self._waiting_since.pop(name, None)
        for data, mod in self._waiting.pop(name, {}).values():
            self._maybe_start_pod(data, mod, now)

    # ---- pod lifecycle -------------------------------------------------

    def _maybe_start_pod(
        self, data: bytes, mod_revision: int, now: float = 0.0
    ) -> None:
        obj = json.loads(data)
        node = obj.get("spec", {}).get("nodeName")
        if not node:
            return
        if obj.get("status", {}).get("phase") != "Pending":
            # Already Running (e.g. a relist after resync): keep it in the
            # running set if it's on one of our nodes.
            if node in self.nodes:
                self.running_pods.add(
                    f"{obj['metadata'].get('namespace', 'default')}/"
                    f"{obj['metadata']['name']}"
                )
            return
        if node in self._foreign:
            return            # another group's node — not ours to start
        if node not in self.nodes:
            # Unknown node: its watch event hasn't been applied yet (node
            # and pod watches are separate streams).  Park the pod; the
            # node's PUT resolves it — _adopt replays if ours, the
            # foreign branch in tick() discards if not.
            pk = (f"{obj['metadata'].get('namespace', 'default')}/"
                  f"{obj['metadata']['name']}")
            self._waiting.setdefault(node, {})[pk] = (data, mod_revision)
            self._waiting_since.setdefault(node, now)
            return
        key = pod_key(obj["metadata"].get("namespace", "default"),
                      obj["metadata"]["name"])
        obj["status"]["phase"] = "Running"
        obj["status"]["conditions"] = [{"type": "Ready", "status": "True"}]
        ok, _, _ = self.store.cas(
            key, json.dumps(obj, separators=(",", ":")).encode(),
            required_mod=mod_revision,
        )
        if ok:
            self.running_pods.add(f"{obj['metadata'].get('namespace', 'default')}/"
                                  f"{obj['metadata']['name']}")
            self._started_total += 1
            _PODS_STARTED.inc(group=self.group)
        # CAS failure: someone updated the pod concurrently; the new
        # revision arrives via the watch and is retried there.

    # ---- tick ----------------------------------------------------------

    def tick(self, now: float) -> dict:
        """Advance the simulator: drain watches, renew due leases, start
        newly bound pods.  Returns per-tick stats."""
        # ``started`` is a monotonic counter delta, NOT a set-size delta:
        # a resync clears and rebuilds running_pods, which would make any
        # length-based delta meaningless for the tick that resyncs.
        started0 = self._started_total
        if (
            self._nodes_watch is None      # earlier resync attempt failed
            or self._pods_watch is None
            or self._nodes_watch.dropped
            or self._pods_watch.dropped
        ):
            # Watch overflow or broken stream (store restart): events were
            # lost — reset soft state and relist, like the coordinator.
            # A failed relist (store still down) leaves the watches None
            # and is retried next tick instead of wedging the controller.
            self.close()
            self.nodes.clear()
            self._next_renewal.clear()
            self._waiting.clear()
            self._waiting_since.clear()
            self._foreign.clear()
            self.running_pods.clear()
            try:
                self.bootstrap(now)
            except Exception:
                log.warning(
                    "resync relist failed; retrying next tick", exc_info=True
                )
                return {"renewed": 0, "started": 0, "nodes": 0}
        renewed = 0
        for ev in drain_events(self._nodes_watch):
            name = ev.kv.key[len(NODES_PREFIX):].decode()
            if ev.type == "PUT":
                obj = json.loads(ev.kv.value)
                if self._owns(obj):
                    if name not in self.nodes:
                        self._adopt(name, now)
                else:
                    if name in self.nodes:
                        self._drop(name)
                    self._foreign.add(name)
                    self._waiting.pop(name, None)
                    self._waiting_since.pop(name, None)
            else:
                self._foreign.discard(name)
                if name in self.nodes:
                    self._drop(name)
        for ev in drain_events(self._pods_watch):
            if ev.type == "PUT":
                self._maybe_start_pod(ev.kv.value, ev.kv.mod_revision, now)
            else:
                key = ev.kv.key[len(PODS_PREFIX):].decode()
                self.running_pods.discard(key)
                for waiting in self._waiting.values():
                    waiting.pop(key, None)
        # Bound the parking lot: pods bound to a node name that never
        # appears (typo'd / external writer) would otherwise be retained
        # forever.  Node and pod watches are separate streams, so a large
        # bind wave can legitimately park >cap pods for a tick or two —
        # eviction therefore requires BOTH pressure (total over the cap)
        # and age (the node stayed unseen past a grace period).
        if sum(len(w) for w in self._waiting.values()) > MAX_WAITING_PODS:
            for node in list(self._waiting):
                since = self._waiting_since.get(node, now)
                if now - since < WAITING_GRACE_S:
                    continue
                dropped = self._waiting.pop(node)
                self._waiting_since.pop(node, None)
                log.warning(
                    "evicting %d pod(s) parked %.0fs on never-seen node %r",
                    len(dropped), now - since, node,
                )

        # Renew every due lease in ONE wave: leases are the dominant
        # 1M-node write load (100K/s in the reference), and per-lease
        # RPCs cap a wire-connected controller at the per-request rate;
        # the BatchKV frame path carries the same wave at ~50x that
        # (store/remote.py put_batch).
        due_items = []
        due_names = []
        delays = []
        for name, due in self._next_renewal.items():
            if due <= now:
                due_items.append(
                    (lease_key(LEASE_NS, name), self._lease_value(name, now))
                )
                due_names.append(name)
                delays.append(now - due)
        if due_items:
            try:
                put_batch = getattr(self.store, "put_batch", None)
                if put_batch is not None:
                    put_batch(due_items)
                else:
                    for k, v in due_items:
                        self.store.put(k, v)
            except Exception:
                # Schedules advance only on success: a failed wave keeps
                # every lease due, so the next tick retries instead of
                # silently slipping them a whole interval (a slip can
                # exceed leaseDurationSeconds — a false node death).
                log.warning(
                    "lease renewal wave failed; %d lease(s) stay due",
                    len(due_items), exc_info=True,
                )
            else:
                for name in due_names:
                    self._next_renewal[name] = now + self.renew_interval_s
                _LEASE_DELAY.observe_many(delays, group=self.group)
                _LEASE_RENEWALS.inc(len(due_items), group=self.group)
                renewed += len(due_items)
        return {
            "renewed": renewed,
            "started": self._started_total - started0,
            "nodes": len(self.nodes),
        }

    def close(self) -> None:
        """Cancel store watches (deregisters native/remote watchers)."""
        for w in (self._nodes_watch, self._pods_watch):
            if w is not None:
                w.cancel()
        self._nodes_watch = self._pods_watch = None

    def _drop(self, name: str) -> None:
        self.nodes.discard(name)
        self._next_renewal.pop(name, None)
        self._waiting.pop(name, None)
        self._waiting_since.pop(name, None)
        self.store.delete(lease_key(LEASE_NS, name))

    def _lease_value(self, name: str, now: float) -> bytes:
        return json.dumps(
            {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": name, "namespace": LEASE_NS},
                "spec": {
                    "holderIdentity": name,
                    "leaseDurationSeconds": self.lease_duration_s,
                    "renewTime": now,
                },
            },
            separators=(",", ":"),
        ).encode()

