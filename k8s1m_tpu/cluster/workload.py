"""Synthetic pod workloads — the make_pods equivalent.

The reference floods the cluster with uniform pods carrying an owner-ref
chain and schedulerName dist-scheduler (reference kwok/make_pods/main.go:109-172).
Here a workload is a generator of PodInfo lists sized to the engine's batch.

**Tenant dimension** (ROADMAP item 2): "millions of users" is thousands
of tenants, not one queue.  ``zipf_weights`` / ``tenant_assignments``
turn a pod-index sequence into a seed-deterministic tenant stream with
zipf-skewed tenant sizes and three arrival shapes — ``steady`` (the
mix is constant), ``diurnal`` (each tenant's offered rate follows a
phase-shifted day curve), ``flash`` (tenant 0 flash-crowds to 10x its
weight for the middle fifth of the sequence).  The paced producers in
sched_bench/soak emit pods in index order, so position in the sequence
IS arrival time and the schedules reproduce exactly by seed.
"""

from __future__ import annotations

import math
import random

from k8s1m_tpu.config import (
    SEL_OP_IN,
    SEL_OP_NOT_IN,
    SPREAD_DO_NOT_SCHEDULE,
    TOPO_HOSTNAME,
    TOPO_ZONE,
)
from k8s1m_tpu.snapshot.constraints import ConstraintTracker
from k8s1m_tpu.snapshot.node_table import REGION_LABEL, ZONE_LABEL
from k8s1m_tpu.snapshot.pod_encoding import (
    AffinityTermRef,
    NodeSelectorTerm,
    PodInfo,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SpreadConstraintRef,
)


TENANT_SCHEDULES = ("steady", "diurnal", "flash")


def zipf_weights(tenants: int, skew: float = 1.0) -> list[float]:
    """Zipf-skewed tenant sizes: weight of tenant t is 1/(t+1)^skew,
    normalized to sum 1.  skew=0 is uniform; skew ~1 is the classic
    heavy-head shape real multi-tenant traffic shows."""
    if tenants < 1:
        raise ValueError("tenants must be >= 1")
    w = [1.0 / (t + 1) ** skew for t in range(tenants)]
    s = sum(w)
    return [x / s for x in w]


def tenant_rate_multipliers(
    schedule: str, frac: float, tenants: int
) -> list[float]:
    """Per-tenant offered-rate multiplier at position ``frac`` in [0,1)
    of the sequence (multiplies the zipf base weight):

    - ``steady``  — 1.0 everywhere.
    - ``diurnal`` — 1 + 0.8*sin(2*pi*(2*frac + t/T)): two "days" over
      the sequence, each tenant's peak phase-shifted, so tenant mixes
      rotate the way timezone-spread user bases do.
    - ``flash``   — tenant 0 jumps to 10x for frac in [0.4, 0.6): the
      flash-crowd the weighted-fair admission must contain.
    """
    if schedule == "steady":
        return [1.0] * tenants
    if schedule == "diurnal":
        return [
            1.0 + 0.8 * math.sin(2.0 * math.pi * (2.0 * frac + t / tenants))
            for t in range(tenants)
        ]
    if schedule == "flash":
        m = [1.0] * tenants
        if 0.4 <= frac < 0.6:
            m[0] = 10.0
        return m
    raise ValueError(f"unknown tenant schedule {schedule!r} "
                     f"(want one of {TENANT_SCHEDULES})")


def tenant_assignments(
    count: int,
    tenants: int,
    *,
    skew: float = 1.0,
    seed: int = 0,
    schedule: str = "steady",
) -> list[int]:
    """Tenant id per pod index — deterministic by (seed, shape args).

    The producers emit pods in index order at their paced rate, so the
    index axis is the arrival-time axis: a diurnal mix or a flash crowd
    lands in the right part of the run without any wall clock."""
    base = zipf_weights(tenants, skew)
    rng = random.Random(seed ^ 0x7E4A47)
    ids = list(range(tenants))
    out: list[int] = []
    # Re-derive the mixture every 256 pods: plenty of resolution for
    # schedules that vary over the whole sequence — and ONE weighted
    # draw of the whole block (random.choices rebuilds its cumulative-
    # weight table per call, so per-pod draws would cost
    # O(count x tenants)).
    step = 256
    for off in range(0, count, step):
        frac = off / max(count, 1)
        mult = tenant_rate_multipliers(schedule, frac, tenants)
        weights = [b * m for b, m in zip(base, mult)]
        out.extend(rng.choices(
            ids, weights=weights, k=min(step, count - off)
        ))
    return out


def uniform_pods(
    count: int,
    *,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    name_prefix: str = "pod",
    namespace: str = "default",
) -> list[PodInfo]:
    return [
        PodInfo(
            name=f"{name_prefix}-{i}",
            namespace=namespace,
            cpu_milli=cpu_milli,
            mem_kib=mem_kib,
        )
        for i in range(count)
    ]


def node_affinity_pods(
    count: int,
    *,
    zones: int = 64,
    regions: int = 8,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    name_prefix: str = "aff-pod",
    namespace: str = "default",
) -> list[PodInfo]:
    """Pods exercising the NodeAffinity plugin against KWOK node labels
    (populate_kwok_nodes writes hostname/zone/region): each pod REQUIRES
    one of two zones (In) while excluding one region (NotIn), and PREFERS
    its primary zone — so the kernel's required-term OR, value sets, and
    preferred-term scoring all run with live data, like BASELINE config 2."""
    out = []
    for i in range(count):
        z1, z2 = i % zones, (i + zones // 2) % zones
        out.append(
            PodInfo(
                name=f"{name_prefix}-{i}",
                namespace=namespace,
                cpu_milli=cpu_milli,
                mem_kib=mem_kib,
                required_terms=[
                    NodeSelectorTerm([
                        SelectorRequirement(
                            ZONE_LABEL, SEL_OP_IN, [f"zone-{z1}", f"zone-{z2}"]
                        ),
                        SelectorRequirement(
                            REGION_LABEL, SEL_OP_NOT_IN,
                            [f"region-{(i + 1) % regions}"],
                        ),
                    ])
                ],
                preferred_terms=[
                    PreferredSchedulingTerm(
                        2,
                        NodeSelectorTerm([
                            SelectorRequirement(
                                ZONE_LABEL, SEL_OP_IN, [f"zone-{z1}"]
                            )
                        ]),
                    )
                ],
            )
        )
    return out


def spread_deployment(
    tracker: ConstraintTracker,
    name: str,
    replicas: int,
    *,
    namespace: str = "default",
    topo: int = TOPO_ZONE,
    max_skew: int = 1,
    mode: int = SPREAD_DO_NOT_SCHEDULE,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    start: int = 0,
) -> list[PodInfo]:
    """Replicas of a Deployment with a topologySpreadConstraint on its own
    ``app=<name>`` selector — BASELINE.json config 3's workload shape."""
    selector = {"app": name}
    cid = tracker.spread_slot(namespace, selector, topo)
    pods = []
    for i in range(start, start + replicas):
        labels = dict(selector)
        pods.append(PodInfo(
            name=f"{name}-{i}", namespace=namespace,
            cpu_milli=cpu_milli, mem_kib=mem_kib, labels=labels,
            spread_refs=[SpreadConstraintRef(cid, topo, max_skew, mode, True)],
            spread_incs=tracker.spread_matches(namespace, labels),
            ipa_incs=tracker.affinity_matches(namespace, labels),
        ))
    return pods


def affinity_deployment(
    tracker: ConstraintTracker,
    name: str,
    replicas: int,
    *,
    namespace: str = "default",
    target: dict[str, str] | None = None,
    topo: int = TOPO_HOSTNAME,
    required: bool = True,
    anti: bool = False,
    weight: int = 1,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    start: int = 0,
) -> list[PodInfo]:
    """Replicas carrying one (anti)affinity term — config 4's shape.

    ``target`` defaults to the deployment's own ``app=<name>`` selector
    (self-affinity / self-anti-affinity, the common Deployment pattern).
    """
    selector = dict(target) if target is not None else {"app": name}
    tid = tracker.affinity_slot(namespace, selector, topo)
    pods = []
    for i in range(start, start + replicas):
        labels = {"app": name}
        pods.append(PodInfo(
            name=f"{name}-{i}", namespace=namespace,
            cpu_milli=cpu_milli, mem_kib=mem_kib, labels=labels,
            affinity_refs=[AffinityTermRef(
                tid, topo, required, anti, weight,
                self_match=ConstraintTracker.selector_matches(selector, labels),
            )],
            spread_incs=tracker.spread_matches(namespace, labels),
            ipa_incs=tracker.affinity_matches(namespace, labels),
        ))
    return pods
