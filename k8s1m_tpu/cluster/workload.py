"""Synthetic pod workloads — the make_pods equivalent.

The reference floods the cluster with uniform pods carrying an owner-ref
chain and schedulerName dist-scheduler (reference kwok/make_pods/main.go:109-172).
Here a workload is a generator of PodInfo lists sized to the engine's batch.
"""

from __future__ import annotations

from k8s1m_tpu.config import (
    SEL_OP_IN,
    SEL_OP_NOT_IN,
    SPREAD_DO_NOT_SCHEDULE,
    TOPO_HOSTNAME,
    TOPO_ZONE,
)
from k8s1m_tpu.snapshot.constraints import ConstraintTracker
from k8s1m_tpu.snapshot.node_table import REGION_LABEL, ZONE_LABEL
from k8s1m_tpu.snapshot.pod_encoding import (
    AffinityTermRef,
    NodeSelectorTerm,
    PodInfo,
    PreferredSchedulingTerm,
    SelectorRequirement,
    SpreadConstraintRef,
)


def uniform_pods(
    count: int,
    *,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    name_prefix: str = "pod",
    namespace: str = "default",
) -> list[PodInfo]:
    return [
        PodInfo(
            name=f"{name_prefix}-{i}",
            namespace=namespace,
            cpu_milli=cpu_milli,
            mem_kib=mem_kib,
        )
        for i in range(count)
    ]


def node_affinity_pods(
    count: int,
    *,
    zones: int = 64,
    regions: int = 8,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    name_prefix: str = "aff-pod",
    namespace: str = "default",
) -> list[PodInfo]:
    """Pods exercising the NodeAffinity plugin against KWOK node labels
    (populate_kwok_nodes writes hostname/zone/region): each pod REQUIRES
    one of two zones (In) while excluding one region (NotIn), and PREFERS
    its primary zone — so the kernel's required-term OR, value sets, and
    preferred-term scoring all run with live data, like BASELINE config 2."""
    out = []
    for i in range(count):
        z1, z2 = i % zones, (i + zones // 2) % zones
        out.append(
            PodInfo(
                name=f"{name_prefix}-{i}",
                namespace=namespace,
                cpu_milli=cpu_milli,
                mem_kib=mem_kib,
                required_terms=[
                    NodeSelectorTerm([
                        SelectorRequirement(
                            ZONE_LABEL, SEL_OP_IN, [f"zone-{z1}", f"zone-{z2}"]
                        ),
                        SelectorRequirement(
                            REGION_LABEL, SEL_OP_NOT_IN,
                            [f"region-{(i + 1) % regions}"],
                        ),
                    ])
                ],
                preferred_terms=[
                    PreferredSchedulingTerm(
                        2,
                        NodeSelectorTerm([
                            SelectorRequirement(
                                ZONE_LABEL, SEL_OP_IN, [f"zone-{z1}"]
                            )
                        ]),
                    )
                ],
            )
        )
    return out


def spread_deployment(
    tracker: ConstraintTracker,
    name: str,
    replicas: int,
    *,
    namespace: str = "default",
    topo: int = TOPO_ZONE,
    max_skew: int = 1,
    mode: int = SPREAD_DO_NOT_SCHEDULE,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    start: int = 0,
) -> list[PodInfo]:
    """Replicas of a Deployment with a topologySpreadConstraint on its own
    ``app=<name>`` selector — BASELINE.json config 3's workload shape."""
    selector = {"app": name}
    cid = tracker.spread_slot(namespace, selector, topo)
    pods = []
    for i in range(start, start + replicas):
        labels = dict(selector)
        pods.append(PodInfo(
            name=f"{name}-{i}", namespace=namespace,
            cpu_milli=cpu_milli, mem_kib=mem_kib, labels=labels,
            spread_refs=[SpreadConstraintRef(cid, topo, max_skew, mode, True)],
            spread_incs=tracker.spread_matches(namespace, labels),
            ipa_incs=tracker.affinity_matches(namespace, labels),
        ))
    return pods


def affinity_deployment(
    tracker: ConstraintTracker,
    name: str,
    replicas: int,
    *,
    namespace: str = "default",
    target: dict[str, str] | None = None,
    topo: int = TOPO_HOSTNAME,
    required: bool = True,
    anti: bool = False,
    weight: int = 1,
    cpu_milli: int = 100,
    mem_kib: int = 200 << 10,
    start: int = 0,
) -> list[PodInfo]:
    """Replicas carrying one (anti)affinity term — config 4's shape.

    ``target`` defaults to the deployment's own ``app=<name>`` selector
    (self-affinity / self-anti-affinity, the common Deployment pattern).
    """
    selector = dict(target) if target is not None else {"app": name}
    tid = tracker.affinity_slot(namespace, selector, topo)
    pods = []
    for i in range(start, start + replicas):
        labels = {"app": name}
        pods.append(PodInfo(
            name=f"{name}-{i}", namespace=namespace,
            cpu_milli=cpu_milli, mem_kib=mem_kib, labels=labels,
            affinity_refs=[AffinityTermRef(
                tid, topo, required, anti, weight,
                self_match=ConstraintTracker.selector_matches(selector, labels),
            )],
            spread_incs=tracker.spread_matches(namespace, labels),
            ipa_incs=tracker.affinity_matches(namespace, labels),
        ))
    return pods
