"""Topology-spread and inter-pod-affinity constraint state.

Upstream recomputes "how many matching pods per topology domain" by
walking pod lists at every scheduling cycle — the O(pods x nodes) path
BASELINE.json config 4 calls out.  The TPU design is incremental instead:
constraints (a namespace + labelSelector + topologyKey triple) are
interned host-side into dense slots, and the device keeps *count tables*
per (slot, domain):

- hostname-keyed domains are nodes, so counts are [slots, N] and shard
  with the node axis;
- zone/region-keyed domains are small dense tables, replicated.

Bind commits scatter-add into these tables inside the same jit step that
produced the binds, so the next batch sees them — the equivalent of the
scheduler cache's AssumePod for topology state.  Unbinds (pod deletion)
arrive as negative deltas from the coordinator.

For inter-pod affinity two tables exist per granularity:
- ``tgt``: pods *matched by* the term's selector per domain (evaluating
  the incoming pod's own terms);
- ``own``: pods *carrying* the term per domain (evaluating existing pods'
  required anti-affinity against the incoming pod — upstream's symmetry
  rule).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct

from k8s1m_tpu.config import (
    TOPO_HOSTNAME,
    TOPO_REGION,
    TOPO_ZONE,
    TableSpec,
)


@struct.dataclass
class ConstraintState:
    # PodTopologySpread: matching-pod counts per (constraint slot, domain).
    spread_node: jax.Array    # i32[C, N]
    spread_zone: jax.Array    # i32[C, Z]
    spread_region: jax.Array  # i32[C, R]
    # InterPodAffinity target counts (pods matching the term's selector).
    tgt_node: jax.Array       # i32[A, N]
    tgt_zone: jax.Array       # i32[A, Z]
    tgt_region: jax.Array     # i32[A, R]
    # InterPodAffinity owner counts (pods carrying the term; only required
    # anti-affinity owners matter for the symmetry filter).
    own_node: jax.Array       # i32[A, N]
    own_zone: jax.Array       # i32[A, Z]
    own_region: jax.Array     # i32[A, R]


def empty_constraints(spec: TableSpec) -> ConstraintState:
    c, a = spec.spread_slots, spec.affinity_slots
    n, z, r = spec.max_nodes, spec.max_zones, spec.max_regions
    i32 = jnp.int32
    return ConstraintState(
        spread_node=jnp.zeros((c, n), i32),
        spread_zone=jnp.zeros((c, z), i32),
        spread_region=jnp.zeros((c, r), i32),
        tgt_node=jnp.zeros((a, n), i32),
        tgt_zone=jnp.zeros((a, z), i32),
        tgt_region=jnp.zeros((a, r), i32),
        own_node=jnp.zeros((a, n), i32),
        own_zone=jnp.zeros((a, z), i32),
        own_region=jnp.zeros((a, r), i32),
    )


def slice_constraints(state: ConstraintState, start, chunk: int) -> ConstraintState:
    """Slice the node-domain tables to match a node-table chunk; domain
    tables pass through whole."""
    sl = lambda x: jax.lax.dynamic_slice_in_dim(x, start, chunk, axis=1)
    return state.replace(
        spread_node=sl(state.spread_node),
        tgt_node=sl(state.tgt_node),
        own_node=sl(state.own_node),
    )


# ---- host-side interning ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SelectorKey:
    """Identity of a constraint: namespace + matchLabels + topology key."""

    namespace: str
    match_labels: tuple[tuple[str, str], ...]
    topo: int


class ConstraintTracker:
    """Interns spread constraints and affinity terms into device slots.

    Slots are a small fixed pool (TableSpec.spread_slots/affinity_slots):
    only constraints referenced by in-flight workloads need to live on
    device, mirroring how the reference only materializes plugin state for
    pods it is actively scheduling (CycleState, reference
    pkg/distpermit/distpermit.go:51-56).
    """

    def __init__(self, spec: TableSpec) -> None:
        self.spec = spec
        self._spread: dict[SelectorKey, int] = {}
        self._affinity: dict[SelectorKey, int] = {}

    @staticmethod
    def _key(namespace: str, selector: dict[str, str], topo: int) -> SelectorKey:
        return SelectorKey(namespace, tuple(sorted(selector.items())), topo)

    def spread_slot(self, namespace: str, selector: dict[str, str], topo: int) -> int:
        key = self._key(namespace, selector, topo)
        slot = self._spread.get(key)
        if slot is None:
            slot = len(self._spread)
            if slot >= self.spec.spread_slots:
                raise ValueError("out of spread constraint slots; grow TableSpec.spread_slots")
            self._spread[key] = slot
        return slot

    def affinity_slot(self, namespace: str, selector: dict[str, str], topo: int) -> int:
        key = self._key(namespace, selector, topo)
        slot = self._affinity.get(key)
        if slot is None:
            slot = len(self._affinity)
            if slot >= self.spec.affinity_slots:
                raise ValueError("out of affinity term slots; grow TableSpec.affinity_slots")
            self._affinity[key] = slot
        return slot

    @staticmethod
    def selector_matches(selector: dict[str, str], labels: dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in selector.items())

    def spread_matches(self, namespace: str, labels: dict[str, str]):
        """(slot, topo) of every interned spread constraint matching a pod."""
        return [
            (slot, key.topo)
            for key, slot in self._spread.items()
            if key.namespace == namespace
            and self.selector_matches(dict(key.match_labels), labels)
        ]

    def affinity_matches(self, namespace: str, labels: dict[str, str]):
        return [
            (slot, key.topo)
            for key, slot in self._affinity.items()
            if key.namespace == namespace
            and self.selector_matches(dict(key.match_labels), labels)
        ]


# ---- jit-side commit -------------------------------------------------------


def commit_constraint_binds(
    state: ConstraintState,
    bound_node,   # bool[B] gate for node-domain scatters (shard-local under sharding)
    bound_domain,  # bool[B] gate for zone/region scatters (always global)
    node_row,     # i32[B] (clipped to valid rows where unbound)
    zone,         # i32[B] domain of the bound node
    region,       # i32[B]
    sinc_valid,   # bool[B, SI] pod matches spread constraint sinc_cid[b, j]
    sinc_cid,     # i32[B, SI]
    sinc_topo,    # i32[B, SI]
    iinc_valid,   # bool[B, AI] pod matches affinity term iinc_tid[b, j]
    iinc_tid,     # i32[B, AI]
    iinc_topo,    # i32[B, AI]
    own_valid,    # bool[B, AR] pod carries affinity term own_tid[b, j]
    own_tid,      # i32[B, AR]
    own_topo,     # i32[B, AR]
    sign: int = 1,  # +1 commit, -1 roll back (bind CAS conflict / pod delete)
) -> ConstraintState:
    """Fold a batch's binds into the count tables (one scatter per table)."""

    def flat(x, width):
        return jnp.broadcast_to(x[:, None], (x.shape[0], width)).reshape(-1)

    def apply(node_tab, zone_tab, region_tab, valid, slot, topo):
        b, w = valid.shape
        inc_node = sign * (valid & bound_node[:, None]).astype(jnp.int32).reshape(-1)
        inc_dom = sign * (valid & bound_domain[:, None]).astype(jnp.int32).reshape(-1)
        slot, topo = slot.reshape(-1), topo.reshape(-1)
        node_tab = node_tab.at[slot, flat(node_row, w)].add(
            jnp.where(topo == TOPO_HOSTNAME, inc_node, 0)
        )
        zone_tab = zone_tab.at[slot, flat(zone, w)].add(
            jnp.where(topo == TOPO_ZONE, inc_dom, 0)
        )
        region_tab = region_tab.at[slot, flat(region, w)].add(
            jnp.where(topo == TOPO_REGION, inc_dom, 0)
        )
        return node_tab, zone_tab, region_tab

    sn, sz, sr = apply(
        state.spread_node, state.spread_zone, state.spread_region,
        sinc_valid, sinc_cid, sinc_topo,
    )
    tn, tz, tr = apply(
        state.tgt_node, state.tgt_zone, state.tgt_region,
        iinc_valid, iinc_tid, iinc_topo,
    )
    on, oz, orr = apply(
        state.own_node, state.own_zone, state.own_region,
        own_valid, own_tid, own_topo,
    )
    return ConstraintState(
        spread_node=sn, spread_zone=sz, spread_region=sr,
        tgt_node=tn, tgt_zone=tz, tgt_region=tr,
        own_node=on, own_zone=oz, own_region=orr,
    )
