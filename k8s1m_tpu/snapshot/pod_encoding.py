"""Host-side pod feature compiler: PodInfo -> fixed-shape PodBatch tensors.

The reference scatters each pod's raw protobuf to 256 shards over a relay
tree (reference cmd/dist-scheduler/relay.go:23-178); here a *batch* of pods
is compiled to padded int tensors once and broadcast to the mesh as data.

Two host-side precomputations keep the device hot loop free of string-ish
inner dimensions:

- **Tolerations** are evaluated on the host against every *distinct* taint
  triple the cluster has ever seen (Vocab.taints) and shipped as a
  ``tolerated[B, max_taint_ids]`` bitmask; the device filter is a gather +
  reduce over taint slots, never a (toleration x taint) comparison.
- **Query keys**: every label key referenced by this batch's selectors is
  collected into a per-batch table ``qkey[Q]``.  The device resolves each
  node's (found, value, numeric) for those Q keys once per node chunk, and
  all selector expressions index into that [Q, N] resolution by position —
  the per-node label-slot scan happens once, not once per expression.

Padding conventions (relied on by the kernels):
- an affinity term/expr slot is live iff term_valid/expr_valid;
- expr value sets are padded with NONE_ID, which never equals a live label
  value id (values never seen on any node also encode to NONE_ID, which is
  exactly upstream's "cannot match" behavior).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from k8s1m_tpu.config import (
    DEFAULT_SCHEDULER,
    EFFECT_NONE,
    NO_NUMERIC,
    NONE_ID,
    PodSpec,
    SEL_OP_GT,
    SEL_OP_LT,
    SPREAD_DO_NOT_SCHEDULE,
    TOL_OP_EXISTS,
    TOPO_HOSTNAME,
    TableSpec,
)
from k8s1m_tpu.semantics import pod_tolerates_taint
from k8s1m_tpu.snapshot.interning import Vocab, numeric_of
from k8s1m_tpu.snapshot.node_table import Taint


@dataclasses.dataclass
class Toleration:
    key: str = ""                  # "" tolerates every key (with op Exists)
    op: int = TOL_OP_EXISTS
    value: str = ""
    effect: int = EFFECT_NONE      # EFFECT_NONE tolerates every effect


@dataclasses.dataclass
class SelectorRequirement:
    key: str
    op: int                        # SEL_OP_*
    values: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class NodeSelectorTerm:
    match_expressions: list[SelectorRequirement] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PreferredSchedulingTerm:
    weight: int
    term: NodeSelectorTerm


@dataclasses.dataclass
class SpreadConstraintRef:
    """A pod's reference to an interned topologySpreadConstraint slot."""

    cid: int                       # constraint slot in ConstraintState
    topo: int                      # TOPO_* key
    max_skew: int = 1
    mode: int = SPREAD_DO_NOT_SCHEDULE
    self_match: bool = True        # pod matches the constraint's own selector


@dataclasses.dataclass
class AffinityTermRef:
    """A pod's reference to an interned (anti)affinity term slot."""

    tid: int                       # term slot in ConstraintState
    topo: int = TOPO_HOSTNAME
    required: bool = False
    anti: bool = False
    weight: int = 1                # for preferred terms (1-100)
    self_match: bool = False       # bound pod will itself match this term's selector


@dataclasses.dataclass
class PodInfo:
    """Host-side description of one pending pod."""

    name: str
    namespace: str = "default"
    cpu_milli: int = 100
    mem_kib: int = 200 << 10       # 200 MiB
    scheduler_name: str = DEFAULT_SCHEDULER
    node_name: str | None = None
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: list[Toleration] = dataclasses.field(default_factory=list)
    required_terms: list[NodeSelectorTerm] = dataclasses.field(default_factory=list)
    preferred_terms: list[PreferredSchedulingTerm] = dataclasses.field(default_factory=list)
    spread_refs: list[SpreadConstraintRef] = dataclasses.field(default_factory=list)
    affinity_refs: list[AffinityTermRef] = dataclasses.field(default_factory=list)
    # (slot, topo) pairs of constraints/terms whose selector matches this
    # pod's labels — computed host-side (ConstraintTracker.*_matches) and
    # used by the commit scatter to keep domain counts current.
    spread_incs: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    ipa_incs: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    # spec.priority (PriorityClass value).  Host-side only: consumed by
    # admission shedding and tenancy preemption, never encoded into the
    # device batch.  A nonzero priority makes the stored object
    # non-canonical (the native fast lane is for the plain-pod
    # firehose; priority-bearing pods take the full decode path).
    priority: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@struct.dataclass
class PodBatch:
    """Fixed-shape encoded pod batch (B pods, padded)."""

    valid: jax.Array         # bool[B]
    cpu: jax.Array           # i32[B] milliCPU requested
    mem: jax.Array           # i32[B] KiB requested
    node_name_id: jax.Array  # i32[B] spec.nodeName (NONE_ID = unset)
    # Host-evaluated toleration results per distinct taint triple.
    tolerated: jax.Array     # bool[B, max_taint_ids]
    # Per-batch query-key table: global label-key ids; expressions below
    # store *indices* into this table.
    qkey: jax.Array          # i32[Q]
    # spec.nodeSelector — ANDed exact-match pairs.
    sel_valid: jax.Array     # bool[B, S]   (S = aff_exprs slots reused)
    sel_qidx: jax.Array      # i32[B, S] index into qkey
    sel_val: jax.Array       # i32[B, S] required label value id
    # requiredDuringSchedulingIgnoredDuringExecution — OR of terms, AND of exprs.
    req_term_valid: jax.Array  # bool[B, T]
    req_expr_valid: jax.Array  # bool[B, T, E]
    req_qidx: jax.Array        # i32[B, T, E]
    req_op: jax.Array          # i32[B, T, E]
    req_vals: jax.Array        # i32[B, T, E, V]
    req_num: jax.Array         # i32[B, T, E] parsed value for Gt/Lt
    # preferredDuringScheduling terms (single-term each, weighted 1-100).
    pref_term_valid: jax.Array  # bool[B, P]
    pref_weight: jax.Array      # i32[B, P]
    pref_expr_valid: jax.Array  # bool[B, P, E]
    pref_qidx: jax.Array        # i32[B, P, E]
    pref_op: jax.Array          # i32[B, P, E]
    pref_vals: jax.Array        # i32[B, P, E, V]
    pref_num: jax.Array         # i32[B, P, E]
    # Topology-spread constraint references (slots in ConstraintState).
    spread_valid: jax.Array     # bool[B, SR]
    spread_cid: jax.Array       # i32[B, SR]
    spread_topo: jax.Array      # i32[B, SR]
    spread_max_skew: jax.Array  # i32[B, SR]
    spread_mode: jax.Array      # i32[B, SR]
    spread_self: jax.Array      # bool[B, SR]
    # Inter-pod (anti)affinity term references.
    ipa_valid: jax.Array        # bool[B, AR]
    ipa_tid: jax.Array          # i32[B, AR]
    ipa_topo: jax.Array         # i32[B, AR]
    ipa_required: jax.Array     # bool[B, AR]
    ipa_anti: jax.Array         # bool[B, AR]
    ipa_weight: jax.Array       # i32[B, AR]
    ipa_self: jax.Array         # bool[B, AR]
    # Constraints/terms whose selector matches this pod (commit increments).
    sinc_valid: jax.Array       # bool[B, SI]
    sinc_cid: jax.Array         # i32[B, SI]
    sinc_topo: jax.Array        # i32[B, SI]
    iinc_valid: jax.Array       # bool[B, AI]
    iinc_tid: jax.Array         # i32[B, AI]
    iinc_topo: jax.Array        # i32[B, AI]

    @property
    def batch(self) -> int:
        return self.valid.shape[0]


# SHAPE-ONLY by construction — deliberately NOT keyed by vocab
# generation: every shape below derives from PodSpec/TableSpec static
# bounds alone (batch, term/expr/value slot counts, max_taint_ids).  No
# interned id ever flows into a shape — ids are array *contents*, sized
# by the static bounds — so vocab growth can never make a cached spec
# stale.  Anything content-dependent (the hotfeed template cache) must
# key on Vocab.generation() instead; see snapshot/hotfeed.py.
@functools.lru_cache(maxsize=16)
def batch_field_specs(
    s: PodSpec, t: TableSpec
) -> tuple[tuple[str, bool, tuple[int, ...]], ...]:
    """(name, is_bool, shape) for every PodBatch leaf, in field order.

    Single source of truth for the host-side allocation (encode), the
    packed host->device transfer (pack/unpack), and PodBatch itself —
    the packed layout cannot drift from the dataclass.
    """
    b = s.batch
    shapes: dict[str, tuple[bool, tuple[int, ...]]] = dict(
        valid=(True, (b,)), cpu=(False, (b,)), mem=(False, (b,)),
        node_name_id=(False, (b,)),
        tolerated=(True, (b, t.max_taint_ids)),
        qkey=(False, (s.query_keys,)),
        sel_valid=(True, (b, s.aff_exprs)),
        sel_qidx=(False, (b, s.aff_exprs)),
        sel_val=(False, (b, s.aff_exprs)),
        req_term_valid=(True, (b, s.aff_terms)),
        req_expr_valid=(True, (b, s.aff_terms, s.aff_exprs)),
        req_qidx=(False, (b, s.aff_terms, s.aff_exprs)),
        req_op=(False, (b, s.aff_terms, s.aff_exprs)),
        req_vals=(False, (b, s.aff_terms, s.aff_exprs, s.aff_values)),
        req_num=(False, (b, s.aff_terms, s.aff_exprs)),
        pref_term_valid=(True, (b, s.pref_terms)),
        pref_weight=(False, (b, s.pref_terms)),
        pref_expr_valid=(True, (b, s.pref_terms, s.aff_exprs)),
        pref_qidx=(False, (b, s.pref_terms, s.aff_exprs)),
        pref_op=(False, (b, s.pref_terms, s.aff_exprs)),
        pref_vals=(False, (b, s.pref_terms, s.aff_exprs, s.aff_values)),
        pref_num=(False, (b, s.pref_terms, s.aff_exprs)),
        spread_valid=(True, (b, s.spread_refs)),
        spread_cid=(False, (b, s.spread_refs)),
        spread_topo=(False, (b, s.spread_refs)),
        spread_max_skew=(False, (b, s.spread_refs)),
        spread_mode=(False, (b, s.spread_refs)),
        spread_self=(True, (b, s.spread_refs)),
        ipa_valid=(True, (b, s.affinity_refs)),
        ipa_tid=(False, (b, s.affinity_refs)),
        ipa_topo=(False, (b, s.affinity_refs)),
        ipa_required=(True, (b, s.affinity_refs)),
        ipa_anti=(True, (b, s.affinity_refs)),
        ipa_weight=(False, (b, s.affinity_refs)),
        ipa_self=(True, (b, s.affinity_refs)),
        sinc_valid=(True, (b, s.spread_incs)),
        sinc_cid=(False, (b, s.spread_incs)),
        sinc_topo=(False, (b, s.spread_incs)),
        iinc_valid=(True, (b, s.ipa_incs)),
        iinc_tid=(False, (b, s.ipa_incs)),
        iinc_topo=(False, (b, s.ipa_incs)),
    )
    names = [f.name for f in dataclasses.fields(PodBatch)]
    assert set(names) == set(shapes), set(names) ^ set(shapes)
    return tuple((n, *shapes[n]) for n in names)


# Field groups for sparse transfer.  A group is included in the packed
# buffers only when some pod in the wave actually sets it (detected from
# its sentinel array); excluded groups materialize as zeros inside the
# jitted step.  A wave of plain pods — the 1M-KWOK steady state — then
# uploads ~70 KB instead of ~6.5 MB, which through a remote device relay
# is the difference between ~1 ms and ~65 ms per wave.
_GROUP_FIELDS: dict[str, tuple[str, ...]] = {
    "tol": ("tolerated",),
    "sel": ("sel_valid", "sel_qidx", "sel_val"),
    "req": ("req_term_valid", "req_expr_valid", "req_qidx", "req_op",
            "req_vals", "req_num"),
    "pref": ("pref_term_valid", "pref_weight", "pref_expr_valid",
             "pref_qidx", "pref_op", "pref_vals", "pref_num"),
    "spread": ("spread_valid", "spread_cid", "spread_topo",
               "spread_max_skew", "spread_mode", "spread_self"),
    "ipa": ("ipa_valid", "ipa_tid", "ipa_topo", "ipa_required", "ipa_anti",
            "ipa_weight", "ipa_self"),
    "sinc": ("sinc_valid", "sinc_cid", "sinc_topo"),
    "iinc": ("iinc_valid", "iinc_tid", "iinc_topo"),
    "qkey": ("qkey",),
}
_GROUP_SENTINEL: dict[str, str] = {
    "tol": "tolerated", "sel": "sel_valid", "req": "req_term_valid",
    "pref": "pref_term_valid", "spread": "spread_valid",
    "ipa": "ipa_valid", "sinc": "sinc_valid", "iinc": "iinc_valid",
}
_GROUP_OF: dict[str, str] = {
    f: g for g, fs in _GROUP_FIELDS.items() for f in fs
}
ALL_GROUPS: frozenset = frozenset(_GROUP_FIELDS)


@dataclasses.dataclass
class PackedPodBatch:
    """A PodBatch as two host buffers (all-int32, all-bool) holding only
    the field groups this wave uses, plus the full host field dict.

    Through a remote device relay every array argument is its own
    transfer and bandwidth is scarce; two small buffers instead of ~40
    leaves is what makes the per-cycle upload cheap.
    ``unpack_pod_batch`` reverses the packing inside the jitted step
    (``groups`` must be passed through as a static argument — each
    distinct group set is its own compiled executable).
    """

    ints: np.ndarray    # i32[sum of included int field sizes]
    bools: np.ndarray   # bool[sum of included bool field sizes]
    fields: dict        # name -> host np array (full set, zero-filled)
    spec: PodSpec
    table_spec: TableSpec
    groups: frozenset   # included group names
    # Vocab.feed_generation() the encode ran against, stamped by the
    # hotfeed encoder (snapshot/hotfeed.py) — the batch stamp includes
    # node_names because the node_name_id column bakes its lookups.
    # None = vocab-independent (the plain fast lane touches no interned
    # namespace) or a legacy encode; the double-buffered feed compares
    # this against the live feed_generation before handing a pre-staged
    # batch to a wave.
    vocab_gen: int | None = None

    @property
    def batch(self) -> int:
        return self.spec.batch


def unpack_pod_batch(
    ints,
    bools,
    spec: PodSpec,
    table_spec: TableSpec,
    groups: frozenset = ALL_GROUPS,
) -> PodBatch:
    """Rebuild a PodBatch from the packed buffers (jit-traceable).
    Fields of groups not in ``groups`` become zeros."""
    out = {}
    io = bo = 0
    for name, is_bool, shape in batch_field_specs(spec, table_spec):
        group = _GROUP_OF.get(name)
        if group is not None and group not in groups:
            # NUMPY zeros on purpose: under jit these lift to the same
            # XLA constants jnp.zeros would, but they stay statically
            # visible to the filter plugins' _statically_empty check
            # (a jnp.zeros inside a trace is a tracer) — which is what
            # lets absent groups skip at trace time instead of XLA
            # constant-folding a [B, S, N] chain for minutes on CPU.
            out[name] = np.zeros(shape, np.bool_ if is_bool else np.int32)
            continue
        n = math.prod(shape)
        if is_bool:
            out[name] = bools[bo : bo + n].reshape(shape)
            bo += n
        else:
            out[name] = ints[io : io + n].reshape(shape)
            io += n
    return PodBatch(**out)


class PodBatchHost:
    """Compiles a list of PodInfo into one PodBatch."""

    def __init__(self, spec: PodSpec, table_spec: TableSpec, vocab: Vocab) -> None:
        self.spec = spec
        self.table_spec = table_spec
        self.vocab = vocab

    def encode_packed(self, pods: list[PodInfo]) -> PackedPodBatch:
        """Encode into the sparse two-buffer packed form (the
        coordinator's hot path)."""
        specs = batch_field_specs(self.spec, self.table_spec)
        out = {
            name: np.zeros(shape, np.bool_ if is_bool else np.int32)
            for name, is_bool, shape in specs
        }
        self._fill(out, pods)
        groups = {
            g for g, sentinel in _GROUP_SENTINEL.items() if out[sentinel].any()
        }
        if groups & {"sel", "req", "pref"}:
            groups.add("qkey")
        groups = frozenset(groups)
        int_parts, bool_parts = [], []
        for name, is_bool, _shape in specs:
            g = _GROUP_OF.get(name)
            if g is not None and g not in groups:
                continue
            (bool_parts if is_bool else int_parts).append(out[name].ravel())
        ints = (
            np.concatenate(int_parts) if int_parts else np.zeros(0, np.int32)
        )
        bools = (
            np.concatenate(bool_parts) if bool_parts else np.zeros(0, np.bool_)
        )
        return PackedPodBatch(
            ints, bools, out, self.spec, self.table_spec, groups
        )

    def encode_packed_plain(self, cpu, mem) -> PackedPodBatch:
        """Packed encode of a wave of *plain* pods (no selectors,
        tolerations, affinity, or constraint refs) given just their
        cpu/mem columns — fully vectorized, no per-pod Python.

        This is the native-intake fast lane (store/native.py poll_pods):
        canonical label-less pods arrive from the watch as int columns,
        and a wave of them needs exactly two array writes here.  The
        result is identical to encode_packed on the equivalent PodInfos:
        a plain pod tolerates nothing (``tolerated`` stays False, like
        pod_tolerates_taint on an empty toleration list) and sets no
        selector groups.
        """
        specs = batch_field_specs(self.spec, self.table_spec)
        out = {
            name: np.zeros(shape, np.bool_ if is_bool else np.int32)
            for name, is_bool, shape in specs
        }
        n = len(cpu)
        if n > self.spec.batch:
            raise ValueError(f"{n} pods > batch {self.spec.batch}")
        out["valid"][:n] = True
        out["cpu"][:n] = cpu
        out["mem"][:n] = mem
        groups: frozenset = frozenset()
        int_parts, bool_parts = [], []
        for name, is_bool, _shape in specs:
            if _GROUP_OF.get(name) is not None:
                continue
            (bool_parts if is_bool else int_parts).append(out[name].ravel())
        return PackedPodBatch(
            np.concatenate(int_parts), np.concatenate(bool_parts), out,
            self.spec, self.table_spec, groups,
        )

    def encode(self, pods: list[PodInfo]) -> PodBatch:
        specs = batch_field_specs(self.spec, self.table_spec)
        out = {
            name: np.zeros(shape, np.bool_ if is_bool else np.int32)
            for name, is_bool, shape in specs
        }
        self._fill(out, pods)
        return PodBatch(**{k: jnp.asarray(a) for k, a in out.items()})

    def _fill(self, out: dict, pods: list[PodInfo]) -> None:
        s = self.spec
        b = s.batch
        if len(pods) > b:
            raise ValueError(f"{len(pods)} pods > batch {b}")
        v = self.vocab

        # Per-batch query-key table.  Index 0 is reserved for "key NONE"
        # (qkey[0] == NONE_ID, never found on any node) so padded
        # expression slots resolve harmlessly.
        qidx_of: dict[str, int] = {}

        def qidx(key: str) -> int:
            i = qidx_of.get(key)
            if i is None:
                i = len(qidx_of) + 1
                if i >= s.query_keys:
                    raise ValueError(
                        f"batch references >{s.query_keys - 1} distinct selector "
                        "keys; grow PodSpec.query_keys"
                    )
                qidx_of[key] = i
                out["qkey"][i] = v.label_keys.lookup(key)
            return i

        # Scalar columns vectorized (one numpy fancy-write per column, not
        # one per pod): at 10K+ binds/s the per-pod `arr[i] = x` writes in
        # this loop were a measurable slice of the whole pipeline.
        n = len(pods)
        out["valid"][:n] = True
        out["cpu"][:n] = np.fromiter((p.cpu_milli for p in pods), np.int32, n)
        out["mem"][:n] = np.fromiter((p.mem_kib for p in pods), np.int32, n)
        taints = list(v.taints.items())

        for i, pod in enumerate(pods):
            # spec.nodeName naming a node we've never seen must match
            # nothing (not "unset"), hence the -1 sentinel.
            if pod.node_name is not None:
                nid = v.node_names.lookup(pod.node_name)
                out["node_name_id"][i] = nid if nid != NONE_ID else -1
            self._fill_pod(out, i, pod, qidx, taints)

    def _fill_pod(self, out: dict, i: int, pod: PodInfo, qidx, taints) -> None:
        """Encode one pod's structural features into row ``i`` of ``out``.

        Shared between the batch loop above and the hotfeed template
        encoder (snapshot/hotfeed.py encodes each distinct shape ONCE
        through this body, then replays the cached rows with vectorized
        writes) — one source of truth is what makes cached encodes
        byte-identical to uncached by construction."""
        s = self.spec
        v = self.vocab

        # Evaluate this pod's tolerations against every distinct taint
        # triple (upstream: v1.Toleration.ToleratesTaint per node taint).
        # A pod with no tolerations tolerates nothing — skip the
        # per-triple scan instead of evaluating an empty list per triple.
        if taints and pod.tolerations:
            for tid, (tkey, tval, teffect) in taints:
                out["tolerated"][i, tid] = pod_tolerates_taint(
                    pod.tolerations, Taint(tkey, tval, teffect)
                )

        if not (
            pod.node_selector or pod.required_terms or pod.preferred_terms
            or pod.spread_refs or pod.affinity_refs or pod.spread_incs
            or pod.ipa_incs
        ):
            return    # plain pod: everything below stays zero

        if len(pod.node_selector) > s.aff_exprs:
            raise ValueError(f"pod {pod.key}: nodeSelector too large")
        for j, (k, val) in enumerate(sorted(pod.node_selector.items())):
            out["sel_valid"][i, j] = True
            out["sel_qidx"][i, j] = qidx(k)
            out["sel_val"][i, j] = v.label_values.lookup(val)

        if len(pod.required_terms) > s.aff_terms:
            raise ValueError(f"pod {pod.key}: too many required affinity terms")
        for j, term in enumerate(pod.required_terms):
            out["req_term_valid"][i, j] = True
            self._encode_exprs(
                qidx, i, j, term.match_expressions, out["req_expr_valid"],
                out["req_qidx"], out["req_op"], out["req_vals"], out["req_num"],
            )
        if len(pod.preferred_terms) > s.pref_terms:
            raise ValueError(f"pod {pod.key}: too many preferred terms")
        for j, pt in enumerate(pod.preferred_terms):
            out["pref_term_valid"][i, j] = True
            out["pref_weight"][i, j] = pt.weight
            self._encode_exprs(
                qidx, i, j, pt.term.match_expressions, out["pref_expr_valid"],
                out["pref_qidx"], out["pref_op"], out["pref_vals"], out["pref_num"],
            )

        if len(pod.spread_refs) > s.spread_refs:
            raise ValueError(f"pod {pod.key}: too many spread constraints")
        for j, ref in enumerate(pod.spread_refs):
            out["spread_valid"][i, j] = True
            out["spread_cid"][i, j] = ref.cid
            out["spread_topo"][i, j] = ref.topo
            out["spread_max_skew"][i, j] = ref.max_skew
            out["spread_mode"][i, j] = ref.mode
            out["spread_self"][i, j] = ref.self_match
        if len(pod.affinity_refs) > s.affinity_refs:
            raise ValueError(f"pod {pod.key}: too many affinity terms")
        for j, ref in enumerate(pod.affinity_refs):
            out["ipa_valid"][i, j] = True
            out["ipa_tid"][i, j] = ref.tid
            out["ipa_topo"][i, j] = ref.topo
            out["ipa_required"][i, j] = ref.required
            out["ipa_anti"][i, j] = ref.anti
            out["ipa_weight"][i, j] = ref.weight
            out["ipa_self"][i, j] = ref.self_match

        if len(pod.spread_incs) > s.spread_incs:
            raise ValueError(f"pod {pod.key}: too many spread increments")
        for j, (cid, topo) in enumerate(pod.spread_incs):
            out["sinc_valid"][i, j] = True
            out["sinc_cid"][i, j] = cid
            out["sinc_topo"][i, j] = topo
        if len(pod.ipa_incs) > s.ipa_incs:
            raise ValueError(f"pod {pod.key}: too many affinity increments")
        for j, (tid, topo) in enumerate(pod.ipa_incs):
            out["iinc_valid"][i, j] = True
            out["iinc_tid"][i, j] = tid
            out["iinc_topo"][i, j] = topo

    def _encode_exprs(self, qidx, i, j, exprs, expr_valid, qidx_arr, op, vals, num):
        s = self.spec
        v = self.vocab
        if len(exprs) > s.aff_exprs:
            raise ValueError("too many match expressions in a term")
        for e, req in enumerate(exprs):
            expr_valid[i, j, e] = True
            qidx_arr[i, j, e] = qidx(req.key)
            op[i, j, e] = req.op
            if req.op in (SEL_OP_GT, SEL_OP_LT):
                # Missing/unparseable operand -> unsatisfiable (NO_NUMERIC).
                num[i, j, e] = (
                    numeric_of(req.values[0]) if req.values else NO_NUMERIC
                )
            else:
                if len(req.values) > s.aff_values:
                    raise ValueError("too many values in a match expression")
                for k, val in enumerate(req.values):
                    vals[i, j, e, k] = v.label_values.lookup(val)
