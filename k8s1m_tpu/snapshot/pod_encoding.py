"""Host-side pod feature compiler: PodInfo -> fixed-shape PodBatch tensors.

The reference scatters each pod's raw protobuf to 256 shards over a relay
tree (reference cmd/dist-scheduler/relay.go:23-178); here a *batch* of pods
is compiled to padded int tensors once and broadcast to the mesh as data.
Everything string-ish goes through the snapshot Vocab; values never seen on
any node encode to NONE_ID, which naturally cannot match (upstream's
behavior for a selector naming an unknown value).

Padding conventions (checked by the kernels):
- a toleration slot is live iff tol_valid — key id 0 with op Exists is the
  legal "tolerate everything" toleration, so validity is explicit;
- an affinity term/expr slot is live iff term_valid/expr_valid;
- expr_vals is padded with NONE_ID, which never equals a live label value.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from k8s1m_tpu.config import (
    EFFECT_NONE,
    NONE_ID,
    PodSpec,
    SEL_OP_DOES_NOT_EXIST,
    SEL_OP_EXISTS,
    SEL_OP_GT,
    SEL_OP_IN,
    SEL_OP_LT,
    SEL_OP_NOT_IN,
    SPREAD_DO_NOT_SCHEDULE,
    TOL_OP_EQUAL,
    TOL_OP_EXISTS,
    TOPO_HOSTNAME,
)
from k8s1m_tpu.snapshot.interning import Vocab, numeric_of


@dataclasses.dataclass
class Toleration:
    key: str = ""                  # "" tolerates every key (with op Exists)
    op: int = TOL_OP_EXISTS
    value: str = ""
    effect: int = EFFECT_NONE      # EFFECT_NONE tolerates every effect


@dataclasses.dataclass
class SelectorRequirement:
    key: str
    op: int                        # SEL_OP_*
    values: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class NodeSelectorTerm:
    match_expressions: list[SelectorRequirement] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PreferredSchedulingTerm:
    weight: int
    term: NodeSelectorTerm


@dataclasses.dataclass
class SpreadConstraintRef:
    """A pod's reference to an interned topologySpreadConstraint slot."""

    cid: int                       # constraint slot in ConstraintState
    topo: int                      # TOPO_* key
    max_skew: int = 1
    mode: int = SPREAD_DO_NOT_SCHEDULE
    self_match: bool = True        # pod matches the constraint's own selector


@dataclasses.dataclass
class AffinityTermRef:
    """A pod's reference to an interned (anti)affinity term slot."""

    tid: int                       # term slot in ConstraintState
    topo: int = TOPO_HOSTNAME
    required: bool = False
    anti: bool = False
    weight: int = 1                # for preferred terms (1-100)
    self_match: bool = False       # bound pod will itself match this term's selector


@dataclasses.dataclass
class PodInfo:
    """Host-side description of one pending pod."""

    name: str
    namespace: str = "default"
    cpu_milli: int = 100
    mem_kib: int = 200 << 10       # 200 MiB
    node_name: str | None = None
    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: list[Toleration] = dataclasses.field(default_factory=list)
    required_terms: list[NodeSelectorTerm] = dataclasses.field(default_factory=list)
    preferred_terms: list[PreferredSchedulingTerm] = dataclasses.field(default_factory=list)
    spread_refs: list[SpreadConstraintRef] = dataclasses.field(default_factory=list)
    affinity_refs: list[AffinityTermRef] = dataclasses.field(default_factory=list)
    labels: dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@struct.dataclass
class PodBatch:
    """Fixed-shape encoded pod batch (B pods, padded)."""

    valid: jax.Array         # bool[B]
    cpu: jax.Array           # i32[B] milliCPU requested
    mem: jax.Array           # i32[B] KiB requested
    node_name_id: jax.Array  # i32[B] spec.nodeName (NONE_ID = unset)
    # Tolerations.
    tol_valid: jax.Array     # bool[B, TO]
    tol_key: jax.Array       # i32[B, TO]
    tol_val: jax.Array       # i32[B, TO]
    tol_op: jax.Array        # i32[B, TO]
    tol_effect: jax.Array    # i32[B, TO]
    # spec.nodeSelector — ANDed exact-match pairs.
    sel_valid: jax.Array     # bool[B, S]   (S = aff_exprs slots reused)
    sel_key: jax.Array       # i32[B, S]
    sel_val: jax.Array       # i32[B, S]
    # requiredDuringSchedulingIgnoredDuringExecution — OR of terms, AND of exprs.
    req_term_valid: jax.Array  # bool[B, T]
    req_expr_valid: jax.Array  # bool[B, T, E]
    req_key: jax.Array         # i32[B, T, E]
    req_op: jax.Array          # i32[B, T, E]
    req_vals: jax.Array        # i32[B, T, E, V]
    req_num: jax.Array         # i32[B, T, E] parsed value for Gt/Lt
    # preferredDuringScheduling terms (single-term each, weighted).
    pref_term_valid: jax.Array  # bool[B, P]
    pref_weight: jax.Array      # i32[B, P]
    pref_expr_valid: jax.Array  # bool[B, P, E]
    pref_key: jax.Array         # i32[B, P, E]
    pref_op: jax.Array          # i32[B, P, E]
    pref_vals: jax.Array        # i32[B, P, E, V]
    pref_num: jax.Array         # i32[B, P, E]
    # Topology-spread constraint references (slots in ConstraintState).
    spread_valid: jax.Array     # bool[B, SR]
    spread_cid: jax.Array       # i32[B, SR]
    spread_topo: jax.Array      # i32[B, SR]
    spread_max_skew: jax.Array  # i32[B, SR]
    spread_mode: jax.Array      # i32[B, SR]
    spread_self: jax.Array      # bool[B, SR]
    # Inter-pod (anti)affinity term references.
    ipa_valid: jax.Array        # bool[B, AR]
    ipa_tid: jax.Array          # i32[B, AR]
    ipa_topo: jax.Array         # i32[B, AR]
    ipa_required: jax.Array     # bool[B, AR]
    ipa_anti: jax.Array         # bool[B, AR]
    ipa_weight: jax.Array       # i32[B, AR]
    ipa_self: jax.Array         # bool[B, AR]

    @property
    def batch(self) -> int:
        return self.valid.shape[0]


class PodBatchHost:
    """Compiles a list of PodInfo into one PodBatch."""

    def __init__(self, spec: PodSpec, vocab: Vocab) -> None:
        self.spec = spec
        self.vocab = vocab

    def encode(self, pods: list[PodInfo]) -> PodBatch:
        s = self.spec
        b = s.batch
        if len(pods) > b:
            raise ValueError(f"{len(pods)} pods > batch {b}")
        v = self.vocab

        def zi(*shape):
            return np.zeros(shape, np.int32)

        def zb(*shape):
            return np.zeros(shape, np.bool_)

        out = dict(
            valid=zb(b), cpu=zi(b), mem=zi(b), node_name_id=zi(b),
            tol_valid=zb(b, s.tol_slots), tol_key=zi(b, s.tol_slots),
            tol_val=zi(b, s.tol_slots), tol_op=zi(b, s.tol_slots),
            tol_effect=zi(b, s.tol_slots),
            sel_valid=zb(b, s.aff_exprs), sel_key=zi(b, s.aff_exprs),
            sel_val=zi(b, s.aff_exprs),
            req_term_valid=zb(b, s.aff_terms),
            req_expr_valid=zb(b, s.aff_terms, s.aff_exprs),
            req_key=zi(b, s.aff_terms, s.aff_exprs),
            req_op=zi(b, s.aff_terms, s.aff_exprs),
            req_vals=zi(b, s.aff_terms, s.aff_exprs, s.aff_values),
            req_num=zi(b, s.aff_terms, s.aff_exprs),
            pref_term_valid=zb(b, s.pref_terms),
            pref_weight=zi(b, s.pref_terms),
            pref_expr_valid=zb(b, s.pref_terms, s.aff_exprs),
            pref_key=zi(b, s.pref_terms, s.aff_exprs),
            pref_op=zi(b, s.pref_terms, s.aff_exprs),
            pref_vals=zi(b, s.pref_terms, s.aff_exprs, s.aff_values),
            pref_num=zi(b, s.pref_terms, s.aff_exprs),
            spread_valid=zb(b, s.spread_refs), spread_cid=zi(b, s.spread_refs),
            spread_topo=zi(b, s.spread_refs), spread_max_skew=zi(b, s.spread_refs),
            spread_mode=zi(b, s.spread_refs), spread_self=zb(b, s.spread_refs),
            ipa_valid=zb(b, s.affinity_refs), ipa_tid=zi(b, s.affinity_refs),
            ipa_topo=zi(b, s.affinity_refs), ipa_required=zb(b, s.affinity_refs),
            ipa_anti=zb(b, s.affinity_refs), ipa_weight=zi(b, s.affinity_refs),
            ipa_self=zb(b, s.affinity_refs),
        )

        for i, pod in enumerate(pods):
            out["valid"][i] = True
            out["cpu"][i] = pod.cpu_milli
            out["mem"][i] = pod.mem_kib
            out["node_name_id"][i] = v.node_names.lookup(pod.node_name)

            if len(pod.tolerations) > s.tol_slots:
                raise ValueError(f"pod {pod.key}: too many tolerations")
            for j, tol in enumerate(pod.tolerations):
                out["tol_valid"][i, j] = True
                out["tol_key"][i, j] = v.taint_keys.lookup(tol.key or None)
                out["tol_val"][i, j] = v.taint_values.lookup(tol.value)
                out["tol_op"][i, j] = tol.op
                out["tol_effect"][i, j] = tol.effect

            if len(pod.node_selector) > s.aff_exprs:
                raise ValueError(f"pod {pod.key}: nodeSelector too large")
            for j, (k, val) in enumerate(sorted(pod.node_selector.items())):
                out["sel_valid"][i, j] = True
                out["sel_key"][i, j] = v.label_keys.lookup(k)
                out["sel_val"][i, j] = v.label_values.lookup(val)

            self._encode_terms(
                i, pod.required_terms, out["req_term_valid"], out["req_expr_valid"],
                out["req_key"], out["req_op"], out["req_vals"], out["req_num"],
            )
            if len(pod.preferred_terms) > s.pref_terms:
                raise ValueError(f"pod {pod.key}: too many preferred terms")
            for j, pt in enumerate(pod.preferred_terms):
                out["pref_term_valid"][i, j] = True
                out["pref_weight"][i, j] = pt.weight
                self._encode_exprs(
                    i, j, pt.term.match_expressions, out["pref_expr_valid"],
                    out["pref_key"], out["pref_op"], out["pref_vals"], out["pref_num"],
                )

            for j, ref in enumerate(pod.spread_refs):
                out["spread_valid"][i, j] = True
                out["spread_cid"][i, j] = ref.cid
                out["spread_topo"][i, j] = ref.topo
                out["spread_max_skew"][i, j] = ref.max_skew
                out["spread_mode"][i, j] = ref.mode
                out["spread_self"][i, j] = ref.self_match
            for j, ref in enumerate(pod.affinity_refs):
                out["ipa_valid"][i, j] = True
                out["ipa_tid"][i, j] = ref.tid
                out["ipa_topo"][i, j] = ref.topo
                out["ipa_required"][i, j] = ref.required
                out["ipa_anti"][i, j] = ref.anti
                out["ipa_weight"][i, j] = ref.weight
                out["ipa_self"][i, j] = ref.self_match

        return PodBatch(**{k: jnp.asarray(a) for k, a in out.items()})

    def _encode_terms(self, i, terms, term_valid, expr_valid, key, op, vals, num):
        s = self.spec
        if len(terms) > term_valid.shape[1]:
            raise ValueError("too many required affinity terms")
        for j, term in enumerate(terms):
            term_valid[i, j] = True
            self._encode_exprs(i, j, term.match_expressions, expr_valid, key, op, vals, num)

    def _encode_exprs(self, i, j, exprs, expr_valid, key, op, vals, num):
        s = self.spec
        v = self.vocab
        if len(exprs) > s.aff_exprs:
            raise ValueError("too many match expressions in a term")
        for e, req in enumerate(exprs):
            expr_valid[i, j, e] = True
            key[i, j, e] = v.label_keys.lookup(req.key)
            op[i, j, e] = req.op
            if req.op in (SEL_OP_GT, SEL_OP_LT):
                num[i, j, e] = numeric_of(req.values[0]) if req.values else 0
            else:
                if len(req.values) > s.aff_values:
                    raise ValueError("too many values in a match expression")
                for k, val in enumerate(req.values):
                    vals[i, j, e, k] = v.label_values.lookup(val)
