from k8s1m_tpu.snapshot.interning import Interner, Vocab
from k8s1m_tpu.snapshot.node_table import NodeTable, NodeTableHost, NodeInfo, Taint
from k8s1m_tpu.snapshot.pod_encoding import (
    PodBatch,
    PodBatchHost,
    PodInfo,
    Toleration,
    SelectorRequirement,
    NodeSelectorTerm,
    PreferredSchedulingTerm,
)

__all__ = [
    "Interner",
    "Vocab",
    "NodeTable",
    "NodeTableHost",
    "NodeInfo",
    "Taint",
    "PodBatch",
    "PodBatchHost",
    "PodInfo",
    "Toleration",
    "SelectorRequirement",
    "NodeSelectorTerm",
    "PreferredSchedulingTerm",
]
