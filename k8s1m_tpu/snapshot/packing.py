"""Packed device snapshot: bit/byte-packed cold node-table columns.

The devicestate ceiling (ROADMAP item 1): every ``NodeTable`` column is a
full ``i32`` plane, so the cold columns — labels, taint effects, row
validity, small-cardinality scalars — cost 4 bytes per entry for values
that need 2 bits.  This module defines the **packed** production layout:

- ``meta`` word  — row validity (bit 0) and all ``taint_slots`` 2-bit
  taint effects (bits ``1+2t``..``2+2t``) in ONE ``i32`` per node; the
  separate ``valid`` bool plane and the ``i32[N, T]`` ``taint_effect``
  plane disappear.
- label fusion   — ``label_key``/``label_val`` fused into one ``i32``
  word per slot (``val << key_bits | key``) while the vocab fits the
  static bit budget; **fail-closed**: a vocab that outgrows the budget
  (the hotfeed vocab-drift shape) falls back to split words via
  ``PackingOverflow`` — never a silently-aliased id.
- narrow planes  — ``zone``/``region``/``pods_alloc``/``taint_id`` drop
  to ``int16``/``int8`` where the TableSpec bounds (or a runtime range
  check, for ``pods_alloc``) permit.

Unpacking happens ON DEVICE inside the chunk slice (``unpack_chunk``):
both the XLA scan path (engine/cycle._slice_table) and the fused Pallas
kernel (ops/pallas_topk) consume the same packed planes, so HBM holds
only the packed layout and the decode cost rides in VMEM-sized tiles.
Decode∘encode is the identity for every in-range column (property-tested
in tests/test_packing.py), which is what makes the packed cycle
byte-identical to the unpacked one — the same bar as the PR 6
mesh↔single-device gate.

The hot columns (cpu/mem allocatable + the request accounting the assume
chain mutates every wave) stay plain ``i32``: they are scatter/donation
targets, and commit_binds' in-place adds must not pay a decode.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from k8s1m_tpu.config import TableSpec
from k8s1m_tpu.snapshot.node_table import NodeTable, NodeTableHost

# Columns the packed layout compresses, in NodeTable naming.  The
# bytes/node evidence in bench.py / sched_bench compares exactly this
# set between layouts (BENCH acceptance: >= 2x reduction).
COLD_COLUMNS = (
    "label_key", "label_val", "taint_id", "taint_effect", "valid",
    "zone", "region", "pods_alloc",
)

# Default label-fusion bit budget: 4096 distinct label keys and 512K
# distinct label values before the fail-closed split.  key + val bits
# must stay <= 31 so the fused word never touches the sign bit.
DEFAULT_KEY_BITS = 12
DEFAULT_VAL_BITS = 19


class PackingOverflow(ValueError):
    """A value no longer fits its packed width (vocab drift, a node with
    > int16 pods).  The coordinator treats this as the fail-closed
    signal: rebuild the device table under a wider layout (split label
    words, or packing off) — never truncate."""

    def __init__(self, field: str, msg: str):
        super().__init__(msg)
        self.field = field


# Every reason device_packing_fallback_total can carry: the
# PackingOverflow field names (pack_columns_np's range checks) plus the
# coordinator's static fallback (meta word too narrow).  The packed
# layout composes with the mesh path since meshpack, so "mesh" is no
# longer a fallback reason — the sharded table holds the packed planes.
FALLBACK_REASONS = (
    "label_key", "label_val", "taint_id", "taint_effect",
    "zone", "region", "pods_alloc", "taint_slots",
)


def _np_dtype(name: str):
    return np.dtype(name)


@dataclasses.dataclass(frozen=True)
class PackingSpec:
    """Static description of the packed layout (jit cache key material).

    ``mode`` is "packed" here by construction — "off" is represented by
    the absence of a spec (``build_packing_spec`` returning None), so a
    plain ``NodeTable`` never carries dead packing state.
    """

    fuse_labels: bool = True
    key_bits: int = DEFAULT_KEY_BITS
    val_bits: int = DEFAULT_VAL_BITS
    taint_slots: int = 8
    zone_dtype: str = "int16"
    region_dtype: str = "int8"
    pods_dtype: str = "int16"
    taint_id_dtype: str = "int16"


def resolve_packing(arg: str | None = None) -> str:
    """Packing mode from an explicit arg or the K8S1M_PACKING env var.

    Returns "off" or "packed"; unknown values fail loudly (a typo'd env
    var silently running unpacked would invalidate every bytes/node
    number downstream).
    """
    import os

    mode = arg if arg is not None else os.environ.get("K8S1M_PACKING", "off")
    if mode not in ("off", "packed"):
        raise ValueError(f"K8S1M_PACKING/packing must be off|packed, got {mode!r}")
    return mode


def build_packing_spec(
    table_spec: TableSpec,
    vocab=None,
    *,
    fuse_labels: bool = True,
    key_bits: int = DEFAULT_KEY_BITS,
    val_bits: int = DEFAULT_VAL_BITS,
) -> PackingSpec | None:
    """The packed layout this TableSpec (and current vocab) supports.

    Fail-closed decisions happen HERE, statically: a taint_slots count
    whose 2-bit effects don't fit the meta word disables packing
    entirely (None); a vocab already past the label bit budget disables
    fusion (split words).  Runtime drift past these choices surfaces as
    ``PackingOverflow`` at pack time and the coordinator rebuilds.
    """
    if 1 + 2 * table_spec.taint_slots > 31:
        return None     # meta word cannot hold the effects: packing off
    if key_bits + val_bits > 31:
        raise ValueError(
            f"key_bits {key_bits} + val_bits {val_bits} > 31 (sign bit)"
        )
    if vocab is not None:
        # len() is the next id to be interned: fusion is safe only while
        # every PRESENT id fits, with the next intern still in range.
        if (len(vocab.label_keys) >= (1 << key_bits)
                or len(vocab.label_values) >= (1 << val_bits)):
            fuse_labels = False
    return PackingSpec(
        fuse_labels=fuse_labels,
        key_bits=key_bits,
        val_bits=val_bits,
        taint_slots=table_spec.taint_slots,
        zone_dtype="int16" if table_spec.max_zones <= (1 << 15) else "int32",
        region_dtype=(
            "int8" if table_spec.max_regions <= (1 << 7)
            else "int16" if table_spec.max_regions <= (1 << 15)
            else "int32"
        ),
        pods_dtype="int16",
        taint_id_dtype=(
            "int16" if table_spec.max_taint_ids <= (1 << 15) else "int32"
        ),
    )


@struct.dataclass
class DomainView:
    """The three full columns topology.prologue needs, decoded once per
    wave (global domain statistics don't belong in a chunk decode)."""

    valid: jax.Array    # bool[N]
    zone: jax.Array     # i32[N]
    region: jax.Array   # i32[N]


@struct.dataclass
class PackedNodeTable:
    """Device-resident packed snapshot (the production layout).

    Field names are chosen so the pieces the rest of the engine touches
    WITHOUT decoding keep their NodeTable names: ``commit_binds`` updates
    cpu_req/mem_req/pods_req via ``.replace`` and the dirty-row scatter
    addresses columns by name — both work on either layout unchanged.

    When ``spec.fuse_labels`` is True, ``label_key`` holds the fused
    ``val << key_bits | key`` words and ``label_val`` is an empty
    ``i32[N, 0]`` plane (zero HBM; keeps the field set static).
    """

    # Hot i32 planes (donation/scatter targets — never packed).
    cpu_alloc: jax.Array    # i32[N]
    mem_alloc: jax.Array    # i32[N]
    cpu_req: jax.Array      # i32[N]
    mem_req: jax.Array      # i32[N]
    pods_req: jax.Array     # i32[N]
    name_id: jax.Array      # i32[N]
    label_num: jax.Array    # i32[N, L] (numeric parse — full range)
    # Packed cold planes.
    meta: jax.Array         # i32[N] valid bit + 2-bit taint effects
    label_key: jax.Array    # i32[N, L] fused words (or plain keys)
    label_val: jax.Array    # i32[N, L] plain values (or [N, 0])
    taint_id: jax.Array     # int16/i32[N, T]
    zone: jax.Array         # int16/i32[N]
    region: jax.Array       # int8/int16/i32[N]
    pods_alloc: jax.Array   # int16[N]
    spec: PackingSpec = struct.field(pytree_node=False)

    @property
    def num_rows(self) -> int:
        return self.meta.shape[0]

    def free(self):
        """(cpu, mem, pods) still unrequested — NodeTable.free() parity
        (pods decodes from the narrow plane)."""
        return (
            self.cpu_alloc - self.cpu_req,
            self.mem_alloc - self.mem_req,
            self.pods_alloc.astype(jnp.int32) - self.pods_req,
        )

    def domain_view(self) -> DomainView:
        return DomainView(
            valid=(self.meta & 1) != 0,
            zone=self.zone.astype(jnp.int32),
            region=self.region.astype(jnp.int32),
        )


def is_packed(table) -> bool:
    return isinstance(table, PackedNodeTable)


# ---- host-side packing -----------------------------------------------------


def _check_range(field: str, arr: np.ndarray, hi: int) -> None:
    if arr.size and int(arr.max(initial=0)) >= hi:
        raise PackingOverflow(
            field,
            f"{field} id {int(arr.max())} >= packed bound {hi} "
            "(vocab drift past the static bit budget; fail closed and "
            "rebuild under a wider layout)",
        )


def pack_meta_np(valid: np.ndarray, taint_effect: np.ndarray) -> np.ndarray:
    """(valid bool[R], taint_effect i32[R, T]) -> meta i32[R].  Same
    fail-closed contract as every other packed column: an effect value
    past the 2-bit budget raises, never aliases (the current EFFECT_*
    range 0-3 is exactly full — the next constant someone adds must
    widen the layout, not silently bind to tainted nodes)."""
    _check_range("taint_effect", taint_effect, 4)
    meta = valid.astype(np.int32)
    for t in range(taint_effect.shape[1]):
        meta = meta | ((taint_effect[:, t].astype(np.int32) & 3) << (1 + 2 * t))
    return meta


def pack_columns_np(cols: dict, pspec: PackingSpec) -> dict:
    """Pack a dict of host (numpy) NodeTable columns into the packed
    column dict (PackedNodeTable field names).  ``cols`` must hold every
    NodeTable column name present in the output's source set; partial
    dicts (dirty-row deltas) pack whatever subset their keys imply.

    Range checks are the fail-closed gate: ids past the static budget
    raise PackingOverflow instead of aliasing.
    """
    out: dict = {}
    for name in ("cpu_alloc", "mem_alloc", "cpu_req", "mem_req",
                 "pods_req", "name_id", "label_num"):
        if name in cols:
            out[name] = cols[name]
    if "valid" in cols:
        out["meta"] = pack_meta_np(cols["valid"], cols["taint_effect"])
    if "label_key" in cols:
        lk = cols["label_key"]
        lv = cols["label_val"]
        if pspec.fuse_labels:
            _check_range("label_key", lk, 1 << pspec.key_bits)
            _check_range("label_val", lv, 1 << pspec.val_bits)
            out["label_key"] = (
                (lv.astype(np.int32) << pspec.key_bits) | lk.astype(np.int32)
            )
            out["label_val"] = np.zeros((lk.shape[0], 0), np.int32)
        else:
            out["label_key"] = lk
            out["label_val"] = lv
    if "taint_id" in cols:
        dt = _np_dtype(pspec.taint_id_dtype)
        _check_range("taint_id", cols["taint_id"], 1 << (8 * dt.itemsize - 1))
        out["taint_id"] = cols["taint_id"].astype(dt)
    for name, dtype in (
        ("zone", pspec.zone_dtype),
        ("region", pspec.region_dtype),
        ("pods_alloc", pspec.pods_dtype),
    ):
        if name in cols:
            dt = _np_dtype(dtype)
            _check_range(name, cols[name], 1 << (8 * dt.itemsize - 1))
            out[name] = cols[name].astype(dt)
    return out


def pack_table_host(
    host: NodeTableHost, pspec: PackingSpec, sharding=None
) -> PackedNodeTable:
    """Pack the full host mirror into a device-resident PackedNodeTable
    (the packed-mode counterpart of NodeTableHost.to_device).

    ``sharding`` is the sharded entry point (meshpack): pass the
    coordinator's ``NamedSharding(mesh, P("sp"))`` and every packed
    plane — meta word, fused label words, the int16/int8 scalars —
    lands with its row axis sharded over ``sp``, exactly like the plain
    layout; the sharded cycle decodes inside the shard-local chunk
    slice (engine/cycle._slice_table)."""
    cols = {
        name: getattr(host, name)
        for name in (
            "valid", "cpu_alloc", "mem_alloc", "pods_alloc",
            "cpu_req", "mem_req", "pods_req",
            "label_key", "label_val", "label_num",
            "taint_id", "taint_effect", "zone", "region", "name_id",
        )
    }
    packed = pack_columns_np(cols, pspec)

    def put(x):
        return jax.device_put(jnp.asarray(x), sharding) if sharding else jnp.asarray(x)

    return PackedNodeTable(spec=pspec, **{k: put(v) for k, v in packed.items()})


def pack_table_auto(host: NodeTableHost, table_spec: TableSpec, sharding=None):
    """Bench/tool convenience: pack the host mirror under the layout
    this TableSpec + current vocab support, falling back LOUDLY to the
    plain layout when packing cannot apply (taint_slots too wide for
    the meta word).  The coordinator has its own richer path
    (_table_to_device: metrics, mid-run widening); tools that just need
    "a packed table or the closest thing" use this — and must report
    the layout they actually got (is_packed), not the one requested,
    or the committed bytes/node evidence lies."""
    pspec = build_packing_spec(table_spec, host.vocab)
    if pspec is None:
        import logging

        logging.getLogger(__name__).warning(
            "packing requested but taint_slots=%d does not fit the meta "
            "word; building the UNPACKED layout", table_spec.taint_slots,
        )
        return host.to_device(sharding)
    return pack_table_host(host, pspec, sharding)


def pack_row_delta(
    host: NodeTableHost, rows: np.ndarray, pspec: PackingSpec, columns
) -> dict:
    """Packed dirty-row delta for ``scatter_rows``: the packed-layout
    equivalent of ``{c: getattr(host, c)[rows] for c in columns}``.
    ``columns`` is CAP_COLUMNS or ALL_COLUMNS (NodeTable naming); the
    returned dict uses PackedNodeTable field names.  Layout-agnostic on
    the device side by construction: the same delta dict feeds the
    single-device donating scatter and the mesh's sharding-pinned
    donating scatter (parallel/sharded_cycle.make_sharded_scatter) —
    the delta rides replicated and the scatter lands it into the
    sp-sharded packed planes in place."""
    cols = {c: getattr(host, c)[rows] for c in columns}
    return pack_columns_np(cols, pspec)


# ---- device-side unpacking -------------------------------------------------


def unpack_chunk(chunk: PackedNodeTable) -> NodeTable:
    """Decode a packed chunk (or any packed row slice) into the plain
    NodeTable layout the filter/score plugins consume.  Pure jnp — runs
    inside the jitted chunk scan, so the decode lives in the same fused
    pass as the plugins and nothing i32-wide ever lands back in HBM."""
    p = chunk.spec
    meta = chunk.meta
    taint_effect = jnp.stack(
        [(meta >> (1 + 2 * t)) & 3 for t in range(p.taint_slots)], axis=1
    )
    if p.fuse_labels:
        label_key = chunk.label_key & ((1 << p.key_bits) - 1)
        label_val = chunk.label_key >> p.key_bits
    else:
        label_key = chunk.label_key
        label_val = chunk.label_val
    return NodeTable(
        valid=(meta & 1) != 0,
        cpu_alloc=chunk.cpu_alloc,
        mem_alloc=chunk.mem_alloc,
        pods_alloc=chunk.pods_alloc.astype(jnp.int32),
        cpu_req=chunk.cpu_req,
        mem_req=chunk.mem_req,
        pods_req=chunk.pods_req,
        label_key=label_key,
        label_val=label_val,
        label_num=chunk.label_num,
        taint_id=chunk.taint_id.astype(jnp.int32),
        taint_effect=taint_effect,
        zone=chunk.zone.astype(jnp.int32),
        region=chunk.region.astype(jnp.int32),
        name_id=chunk.name_id,
    )


def mask_rows_packed(table: PackedNodeTable, row_mask) -> PackedNodeTable:
    """engine.cycle.mask_rows for the packed layout: rows outside the
    mask become infeasible on both backends (valid bit cleared for the
    XLA filter chain, pods_alloc zeroed for the fused kernel's row-
    validity convention) without touching commit state."""
    return table.replace(
        meta=jnp.where(row_mask, table.meta, table.meta & ~1),
        pods_alloc=jnp.where(
            row_mask, table.pods_alloc,
            jnp.zeros((), table.pods_alloc.dtype),
        ),
    )


# ---- donation evidence -----------------------------------------------------

# The donated hot planes every layout shares (i32[N] scatter/commit
# targets).  XLA's input-output aliasing pairs donated buffers by
# shape/dtype, NOT by field name, so an output column can legitimately
# land in a DIFFERENT donated input's buffer — the in-place signal is
# overlap of the pointer sets, never pointer identity of one column.
_HOT_PLANES = (
    "cpu_alloc", "mem_alloc", "cpu_req", "mem_req", "pods_req", "name_id",
)


def _plane_ptrs(arr):
    """Per-shard buffer pointers of one plane.  A table sharded over the
    mesh's sp axis holds one buffer per (addressable) device, and XLA
    aliases donated buffers shard-by-shard — so the probe must collect
    EVERY shard's pointer, not call the single-device accessor (which
    raises on multi-shard arrays)."""
    shards = getattr(arr, "addressable_shards", None)
    if shards:
        return [s.data.unsafe_buffer_pointer() for s in shards]
    return [arr.unsafe_buffer_pointer()]


def donation_probe(table) -> frozenset:
    """Buffer pointers of the table's donated hot planes — every shard
    of every plane, so the probe covers single-device AND mesh-sharded
    tables — read BEFORE a donating dispatch (evidence probe; reading a
    pointer syncs on the buffer — keep it out of timed windows)."""
    return frozenset(
        p for c in _HOT_PLANES for p in _plane_ptrs(getattr(table, c))
    )


def donation_inplace(table, probe: frozenset) -> bool:
    """True when the post-step table reuses ANY probed input buffer (on
    any shard) — the runtime honored the donation in place; False means
    every plane was copied (e.g. another live reference pinned the
    inputs)."""
    return any(
        p in probe
        for c in _HOT_PLANES for p in _plane_ptrs(getattr(table, c))
    )


# ---- HBM accounting --------------------------------------------------------


def _leaf_bytes(arr) -> int:
    return int(np.prod(arr.shape)) * np.dtype(arr.dtype).itemsize


def hbm_bytes(table) -> int:
    """Total device bytes of a NodeTable or PackedNodeTable."""
    return sum(_leaf_bytes(leaf) for leaf in jax.tree.leaves(table))


_PACKED_COLD = (
    "label_key", "label_val", "taint_id", "meta", "zone", "region",
    "pods_alloc",
)


def cold_bytes_per_node(table) -> float:
    """Bytes/node of the COLD_COLUMNS set under the table's layout —
    the number the >=2x packing acceptance gate compares."""
    names = _PACKED_COLD if is_packed(table) else COLD_COLUMNS
    n = table.num_rows
    return sum(_leaf_bytes(getattr(table, c)) for c in names) / max(n, 1)


def unpacked_cold_bytes(table_spec: TableSpec) -> float:
    """COLD_COLUMNS bytes/node under the plain i32 layout — the fixed
    denominator every packed run's reduction ratio is taken against."""
    l, t = table_spec.label_slots, table_spec.taint_slots
    #      label_key+label_val  taint_id+effect  valid  zone+region+pods
    return 8 * l + 8 * t + 1 + 4 + 4 + 4


def bytes_report(table, table_spec: TableSpec | None = None) -> dict:
    """Layout evidence for bench JSON: layout name, total and cold
    bytes/node, and (given the TableSpec) the reduction ratio against
    the unpacked cold baseline — the >=2x acceptance number."""
    n = max(table.num_rows, 1)
    out = {
        "layout": "packed" if is_packed(table) else "unpacked",
        "hbm_bytes_per_node": round(hbm_bytes(table) / n, 2),
        "cold_bytes_per_node": round(cold_bytes_per_node(table), 3),
    }
    if table_spec is not None:
        out["cold_bytes_reduction"] = round(
            unpacked_cold_bytes(table_spec) / max(out["cold_bytes_per_node"], 1e-9),
            3,
        )
    return out
