"""hotfeed: cached + vectorized pod encoding and an overlapped host feed.

The reference spends its 289-replica fleet mostly on per-pod host work —
proto scatter, predicate setup — to reach ~14K pods/s at 1M nodes
(reference README.adoc:730,783-787).  After the pipelined coordinator
(PR 3) overlapped device waves, the last serial stage of our cycle was
the host feed itself: ``PodBatchHost._fill`` ran nested per-pod/per-expr
Python every cycle, and the coordinator encoded synchronously inline
with dispatch.  Two observations kill that cost:

1. **Pods share shapes.**  In any real or generated load, most pods in
   a batch carry one of a handful of *structural* specs (selectors,
   tolerations, affinity terms, spread/affinity refs) and differ only in
   scalars (cpu, mem, name).  ``EncodeCache`` fingerprints the
   structural parts and caches the encoded template rows; a batch fill
   becomes one vectorized column write per scalar plus one fancy-indexed
   row broadcast per *distinct shape* — per-shape Python, not per-pod.
2. **Encode need not sit on the critical path.**  ``HostFeed`` runs one
   worker thread that encodes the NEXT wave's batch while the current
   wave is in flight on the device; the coordinator claims the
   pre-staged ``PackedPodBatch`` at dispatch time, so ``encode_packed``
   disappears from the cycle's serial section whenever the queue is deep
   enough to stage ahead.

Correctness contracts (enforced by tests/test_hotfeed.py):

- **Byte-identity.**  A cached encode is byte-identical to the uncached
  ``PodBatchHost`` encode of the same pods against the same vocab.
  Templates are built by the SAME ``_fill_pod`` body the uncached path
  runs (one source of truth), and the per-batch query-key table is
  replayed through the cached pods' ``key_seq`` in pod order, so even
  the first-encounter qkey slot assignment matches exactly.
- **Vocab-generation invalidation.**  Templates bake in interned ids
  (``tolerated`` bakes the taint set, selector values bake
  ``label_values`` lookups — an unseen value encodes NONE_ID but would
  encode a real id after a node introduces it).  The cache keys every
  template on ``Vocab.generation()`` and clears wholesale when the
  encode-relevant namespaces grow.  ``spec.nodeName`` is resolved live
  per pod (a scalar column), so node churn never invalidates.
- **No stale handoffs.**  A feed-staged batch is stamped with the
  generation it encoded against (``PackedPodBatch.vocab_gen``); the
  coordinator re-encodes inline (cheap — the cache is warm) if the
  vocab moved between staging and dispatch, or if the queue prefix the
  batch was peeked from changed.  The worker only ever *peeks* the
  queue contents handed to it — the queue itself stays owned by the
  cycle thread, so drivers that poll ``coord.queue`` never lose pods
  into a hidden staging area.

Threading: ``HostFeed`` state is guarded by ``_lock`` (PR 4's
``@guarded_by`` discipline; ``tests/test_hotfeed.py`` audits it), and
the claim/stage protocol guarantees the worker is idle whenever the
cycle thread encodes with feed-owned state.  The worker gets its OWN
encoder instance (own arena); only ``EncodeCache`` is shared, and it is
lock-guarded.  A worker encode torn by concurrent interning is detected
by the generation stamp and discarded — and any template it may have
built is unreachable at the new generation, so torn state cannot leak.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import math
import threading
import time
import weakref

import numpy as np

from k8s1m_tpu.config import NONE_ID
from k8s1m_tpu.lint import guarded_by
from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.snapshot.pod_encoding import (
    _GROUP_OF,
    _GROUP_SENTINEL,
    PackedPodBatch,
    PodBatchHost,
    PodInfo,
    batch_field_specs,
)

log = logging.getLogger("k8s1m.hotfeed")

_ENCODE_SECONDS = Counter(
    "hotfeed_encode_seconds_total",
    "Host pod-encode seconds, by path (inline = on the cycle thread, "
    "feed = hidden in the worker while a wave is in flight)",
    ("path",),
)
_CACHE_HITS = Counter(
    "hotfeed_cache_hits_total",
    "Template-path pods (shape groups of >= TEMPLATE_MIN in a batch) "
    "served from the encode template cache.  Plain pods and small "
    "groups bypass the cache by design and count in neither series",
    (),
)
_CACHE_MISSES = Counter(
    "hotfeed_cache_misses_total",
    "Template-path pods whose structural shape had to be built fresh "
    "(first sight, or a vocab-generation invalidation)", (),
)
_STAGED_USED = Counter(
    "hotfeed_staged_used_total",
    "Waves dispatched from a feed-pre-staged batch (encode off the "
    "critical path)", (),
)
_STALE = Counter(
    "hotfeed_stale_batches_total",
    "Pre-staged batches discarded at claim time, by reason (vocab = "
    "interning moved between staging and dispatch; reordered = the "
    "queue prefix changed; error = the worker encode raised; merge = "
    "dp-shard sub-batches could not merge, e.g. a query-key overflow)",
    ("reason",),
)
_STAGED_DEPTH = Gauge(
    "hotfeed_staged_depth",
    "Batches currently staged or encoding in host feeds (0..1 per feed)",
    (),
)
_LIVE_FEEDS: weakref.WeakSet = weakref.WeakSet()
# Registration and scrape-time snapshot share a lock: WeakSet iteration
# races a concurrent add() from another thread's HostFeed construction
# (RuntimeError: set changed size during iteration).
_FEEDS_LOCK = threading.Lock()


def _feeds_depth() -> int:
    with _FEEDS_LOCK:
        feeds = list(_LIVE_FEEDS)
    return sum(f.depth() for f in feeds)


_STAGED_DEPTH.set_function(_feeds_depth)


def cache_counts() -> tuple[int, int]:
    """(hits, misses) of the shape-template cache so far — podtrace
    reads a delta around one batch's encode so the encode span carries
    the cache-hit/template-path evidence as attributes (process-wide
    counters: with several live coordinators the delta mixes their
    traffic, which only blurs the attrs, never the span timings)."""
    return int(_CACHE_HITS.value()), int(_CACHE_MISSES.value())


# Shared sentinel for the all-zero structural template: a plain pod
# (the 1M-KWOK steady state) writes scalars only, no template at all.
PLAIN = object()

# Pods of one shape in a batch before the template apply beats encoding
# them directly: each template field is its own fancy write (~2us of
# numpy overhead regardless of row count), so a singleton shape pays
# ~17 writes where the direct body pays one encode — measured
# break-even sits around 2-3 pods; 4 keeps a margin.  Both paths are
# byte-identical, this is purely a cost fork.
TEMPLATE_MIN = 4

# Per-pod scalar columns — always filled vectorized, never cached in a
# template (node_name_id is vocab-live by design, see Vocab.generation).
_SCALAR_FIELDS = frozenset({"valid", "cpu", "mem", "node_name_id"})
# Template fields holding *local* query-key indices that must be
# translated through the per-batch qkey permutation at fill time.
_QIDX_FIELDS = frozenset({"sel_qidx", "req_qidx", "pref_qidx"})


# Section separators for the flat fingerprint: variable-length sections
# back to back would be ambiguous ("ab"+"c" vs "a"+"bc"); a singleton
# object between them restores unambiguity at ~zero cost.  Flat tuples
# beat nested ones: one allocation and a C-speed hash instead of ~15
# interior tuples per pod — fingerprinting runs once per pod in the hot
# fill, so its constant factor is the cache's floor.
_SEP = object()


def fingerprint(pod: PodInfo):
    """Hashable key over a pod's structural (template-cacheable) parts.

    Everything that flows into non-scalar encode output is included;
    scalars (cpu, mem, name, nodeName) are deliberately NOT — they are
    patched per pod.  Returns the shared ``PLAIN`` sentinel for the
    all-default shape so the common case costs one tuple of falsy
    checks, not a tuple build.
    """
    if not (
        pod.node_selector or pod.tolerations or pod.required_terms
        or pod.preferred_terms or pod.spread_refs or pod.affinity_refs
        or pod.spread_incs or pod.ipa_incs
    ):
        return PLAIN
    parts: list = [pod.scheduler_name]
    app = parts.append
    if pod.node_selector:
        for kv in sorted(pod.node_selector.items()):
            app(kv)
    app(_SEP)
    for t in pod.tolerations:
        app(t.key); app(t.op); app(t.value); app(t.effect)
    app(_SEP)
    for term in pod.required_terms:
        for e in term.match_expressions:
            app(e.key); app(e.op); app(tuple(e.values))
        app(_SEP)
    app(_SEP)
    for pt in pod.preferred_terms:
        app(pt.weight)
        for e in pt.term.match_expressions:
            app(e.key); app(e.op); app(tuple(e.values))
        app(_SEP)
    app(_SEP)
    for r in pod.spread_refs:
        app(r.cid); app(r.topo); app(r.max_skew); app(r.mode)
        app(r.self_match)
    app(_SEP)
    for r in pod.affinity_refs:
        app(r.tid); app(r.topo); app(r.required); app(r.anti)
        app(r.weight); app(r.self_match)
    app(_SEP)
    parts.extend(pod.spread_incs)
    app(_SEP)
    parts.extend(pod.ipa_incs)
    return tuple(parts)


def shape_key(pod: PodInfo):
    """Delta-plane cache key of a pod's filter+score plane, or None when
    the pod's plane is not cacheable (engine/deltacache.py).

    The plane a pod computes over the node table is a pure function of
    its structural ``fingerprint`` *plus* its request scalars — Fit and
    the allocation scores read cpu/mem — so the key extends the encode
    cache's fingerprint with exactly those.  Not cacheable (None):

    - constraint-coupled pods (spread/affinity refs or incs): their
      mask/score reads the live count tables, which move with every
      constraintful bind ANYWHERE in a domain — row-level dirty
      tracking cannot bound that;
    - ``spec.nodeName`` pods: the baked ``node_name_id`` lookup can
      resolve differently after the name interns (queued pods never
      carry one — the coordinator settles them as bound — so this is a
      guard, not a hot case).
    """
    if (
        pod.spread_refs or pod.affinity_refs
        or pod.spread_incs or pod.ipa_incs
        or pod.node_name is not None
    ):
        return None
    return (fingerprint(pod), pod.cpu_milli, pod.mem_kib)


@dataclasses.dataclass
class _Template:
    """One shape's encoded rows.  ``direct`` rows broadcast verbatim;
    ``qidx`` rows hold pod-local query-key indices (1..K in the pod's
    own first-encounter order; 0 = padding) that the fill translates
    through the batch-level permutation.  All-zero rows are dropped —
    the arena is pre-zeroed, so writing nothing is identical to writing
    zeros.  Row shapes carry no batch dimension: one cache serves every
    power-of-two batch bucket (equal non-batch spec bounds required)."""

    key_seq: tuple[str, ...]
    direct: dict[str, np.ndarray]
    qidx: dict[str, np.ndarray]


# Structural fields written per pod attribute — mirrors the branches of
# PodBatchHost._fill_pod exactly (a field is in a template iff its
# attribute is set; rows that end up all-zero anyway are harmless — the
# fill arena is pre-zeroed, so re-writing zeros is byte-identical).
# Scanning all ~36 fields with .any() per template build was the
# dominant miss cost; this map replaces the scan with attribute checks.
_FIELDS_BY_ATTR: tuple[tuple[str, tuple[str, ...]], ...] = (
    ("tolerations", ("tolerated",)),
    ("node_selector", ("sel_valid", "sel_qidx", "sel_val")),
    ("required_terms", ("req_term_valid", "req_expr_valid", "req_qidx",
                        "req_op", "req_vals", "req_num")),
    ("preferred_terms", ("pref_term_valid", "pref_weight",
                         "pref_expr_valid", "pref_qidx", "pref_op",
                         "pref_vals", "pref_num")),
    ("spread_refs", ("spread_valid", "spread_cid", "spread_topo",
                     "spread_max_skew", "spread_mode", "spread_self")),
    ("affinity_refs", ("ipa_valid", "ipa_tid", "ipa_topo", "ipa_required",
                       "ipa_anti", "ipa_weight", "ipa_self")),
    ("spread_incs", ("sinc_valid", "sinc_cid", "sinc_topo")),
    ("ipa_incs", ("iinc_valid", "iinc_tid", "iinc_topo")),
)


def _build_template(
    encoder: PodBatchHost, pod: PodInfo, tmp: dict
) -> _Template:
    """Encode one pod's structural features through the SAME `_fill_pod`
    body the uncached path runs, against the caller's zeroed batch-1
    scratch (returned dirty in exactly the fields this returns; the
    cache re-zeroes those rows)."""
    key_seq: list[str] = []
    local: dict[str, int] = {}

    def local_qidx(key: str) -> int:
        li = local.get(key)
        if li is None:
            li = len(local) + 1
            if li >= encoder.spec.query_keys:
                # One pod alone overflowing the table fails identically
                # to the uncached batch-level check.
                raise ValueError(
                    f"batch references >{encoder.spec.query_keys - 1} "
                    "distinct selector keys; grow PodSpec.query_keys"
                )
            local[key] = li
            key_seq.append(key)
        return li

    taints = list(encoder.vocab.taints.items())
    encoder._fill_pod(tmp, 0, pod, local_qidx, taints)
    direct: dict[str, np.ndarray] = {}
    qidx: dict[str, np.ndarray] = {}
    for attr, names in _FIELDS_BY_ATTR:
        if not getattr(pod, attr):
            continue
        if attr == "tolerations" and not taints:
            continue    # no taint triples -> the tolerated row is zero
        for name in names:
            # Copy: the row must outlive the shared scratch.
            row = tmp[name][0].copy()
            (qidx if name in _QIDX_FIELDS else direct)[name] = row
    return _Template(tuple(key_seq), direct, qidx)


@guarded_by(_templates="_lock", _gen="_lock")
class EncodeCache:
    """Shape-keyed template cache, cleared when Vocab.generation moves.

    Shared by every encoder of one coordinator (inline buckets, the
    feed's worker encoder, the adjust path) — templates carry no batch
    dimension.  Sharing requires equal non-batch PodSpec bounds and one
    TableSpec; the coordinator's buckets satisfy this by construction.
    Lock-guarded because the feed worker and the cycle thread both
    consult it (the claim/stage protocol keeps their *arena* use
    disjoint, but cache lookups can genuinely overlap).
    """

    def __init__(self, max_shapes: int = 4096) -> None:
        self._lock = threading.Lock()
        self._templates: dict = {}
        self._gen = -1
        self.max_shapes = max_shapes

    def get_or_build(
        self, encoder: "HotPodBatchHost", pod: PodInfo, fp, gen: int
    ) -> tuple[_Template, bool]:
        """(template, was_cached).  Builds under the lock — template
        builds are one-pod encodes, and serializing them keeps a torn
        build from ever being observed half-written (and makes each
        encoder's build scratch safe to reuse)."""
        with self._lock:
            if gen != self._gen:
                self._templates.clear()
                self._gen = gen
            tpl = self._templates.get(fp)
            if tpl is not None:
                return tpl, True
            tmp = encoder._template_scratch()
            clean = False
            try:
                tpl = _build_template(encoder, pod, tmp)
                # Re-zero exactly the rows the build wrote (the kept
                # field set IS the written set, _FIELDS_BY_ATTR).
                for name in tpl.direct:
                    tmp[name][0] = 0
                for name in tpl.qidx:
                    tmp[name][0] = 0
                clean = True
            finally:
                if not clean:
                    # A build that raised mid-fill left unknown rows
                    # dirty; full memset before anyone reuses it.
                    for arr in tmp.values():
                        arr[:] = 0
            if len(self._templates) >= self.max_shapes:
                # Shape storm (adversarial or genuinely unique specs):
                # bound memory by starting over rather than evicting in
                # some order a replay couldn't reproduce.
                self._templates.clear()
            self._templates[fp] = tpl
            return tpl, False

    def __len__(self) -> int:
        with self._lock:
            return len(self._templates)


class HotPodBatchHost(PodBatchHost):
    """Drop-in ``PodBatchHost`` whose fill is shape-cached + vectorized
    and whose packed encode reuses a pre-allocated arena.

    ``encode()``/``encode_packed()`` output is byte-identical to the
    parent's (differential suite: tests/test_hotfeed.py).  The packed
    result's ``fields`` are views into the freshly-concatenated
    ints/bools buffers (excluded groups share read-only zeros), so a
    retiring wave can still read its batch's commit fields after later
    encodes have recycled the arena.
    """

    def __init__(
        self, spec, table_spec, vocab, *,
        cache: EncodeCache | None = None, path: str = "inline",
    ) -> None:
        super().__init__(spec, table_spec, vocab)
        self.cache = cache if cache is not None else EncodeCache()
        self._path = path
        self._arena: dict | None = None
        # What the last _fill wrote (fields, rows, qkey slots) — copied
        # into _arena_dirty only by encode_packed, because _fill also
        # runs against encode()'s fresh dicts and must not clobber the
        # bookkeeping of what is actually smeared across the arena.
        self._fill_dirty: tuple[set[str], int, int] = (set(), 0, 0)
        self._arena_dirty: tuple[set[str], int, int] = (set(), 0, 0)
        self._last_gen = -1
        self._zeros: dict[str, np.ndarray] = {}
        self._tpl_scratch: dict | None = None

    def _template_scratch(self) -> dict:
        """Reusable batch-1 build scratch (only ever touched under the
        EncodeCache lock, which serializes template builds)."""
        if self._tpl_scratch is None:
            s1 = dataclasses.replace(self.spec, batch=1)
            self._tpl_scratch = {
                name: np.zeros(shape, np.bool_ if is_bool else np.int32)
                for name, is_bool, shape in batch_field_specs(
                    s1, self.table_spec
                )
                if name not in _SCALAR_FIELDS and name != "qkey"
            }
        return self._tpl_scratch

    # ---- arena ---------------------------------------------------------

    def _arena_take(self, specs) -> dict:
        """The reusable output dict, with only the regions the PREVIOUS
        packed fill wrote zeroed (rows past the previous pod count were
        never touched; fields no template used stayed zero)."""
        if self._arena is None:
            self._arena = {
                name: np.zeros(shape, np.bool_ if is_bool else np.int32)
                for name, is_bool, shape in specs
            }
        else:
            fields, n, q = self._arena_dirty
            arena = self._arena
            for name in fields:
                arena[name][:n] = 0
            if q:
                arena["qkey"][:q] = 0
        self._arena_dirty = (set(), 0, 0)
        return self._arena

    # ---- cached fill ---------------------------------------------------

    def _fill(self, out: dict, pods: list[PodInfo]) -> None:
        s = self.spec
        b = s.batch
        if len(pods) > b:
            raise ValueError(f"{len(pods)} pods > batch {b}")
        v = self.vocab
        # The batch stamp includes node_names (scalar node_name_id
        # lookups below bake it); the template cache key (gen) must not.
        # Stamp FIRST: an intern landing between the two reads then
        # makes the stamp strictly older than the live feed_generation,
        # so claim() discards — reading gen first would let a batch
        # built from pre-intern templates carry a passing stamp.
        self._last_gen = v.feed_generation()
        gen = v.generation()
        n = len(pods)
        out["valid"][:n] = True
        out["cpu"][:n] = np.fromiter((p.cpu_milli for p in pods), np.int32, n)  # graftlint: disable=hotfeed-no-per-pod-python (scalar column)
        out["mem"][:n] = np.fromiter((p.mem_kib for p in pods), np.int32, n)  # graftlint: disable=hotfeed-no-per-pod-python (scalar column)
        dirty = {"valid", "cpu", "mem"}

        # Per-batch query-key table, replayed in pod order so the slot
        # assignment is byte-identical to the uncached first-encounter
        # walk (a shape's key_seq is its distinct keys in request order;
        # duplicate requests assign nothing, so replaying the distinct
        # sequence reproduces the batch table exactly).
        qidx_of: dict[str, int] = {}

        def qidx(key: str) -> int:
            i = qidx_of.get(key)
            if i is None:
                i = len(qidx_of) + 1
                if i >= s.query_keys:
                    raise ValueError(
                        f"batch references >{s.query_keys - 1} distinct "
                        "selector keys; grow PodSpec.query_keys"
                    )
                qidx_of[key] = i
                out["qkey"][i] = v.label_keys.lookup(key)
            return i

        cache = self.cache
        groups: dict = {}
        taints = None
        # Phase 1 — per-pod: scalar nodeName + fingerprint + grouping,
        # O(shape) dict/tuple work per pod; every field write happens in
        # phase 2, per shape.
        # graftlint: disable=hotfeed-no-per-pod-python (fingerprinting is the irreducible per-pod work; field writes are per-shape in phase 2)
        for i, pod in enumerate(pods):
            if pod.node_name is not None:
                nid = v.node_names.lookup(pod.node_name)
                out["node_name_id"][i] = nid if nid != NONE_ID else -1
                dirty.add("node_name_id")
            fp = fingerprint(pod)
            if fp is PLAIN:
                continue
            members = groups.get(fp)
            if members is None:
                groups[fp] = [(i, pod)]
            else:
                members.append((i, pod))

        # Phase 2 — per shape, in first-encounter (insertion) order.
        # qkey byte-identity holds because a key's first reference in
        # pod order always happens at the first pod of the first shape
        # referencing it — the same position this order replays.
        # Small groups bypass the template machinery entirely: below
        # TEMPLATE_MIN pods, the per-field write overhead plus the
        # cache round trip costs more than the direct uncached body
        # (measured on-host; both paths are byte-identical, this is
        # purely a cost fork).  Big groups pay one fancy write per
        # template field, amortized across the group.
        hits = misses = 0
        for fp, members in groups.items():
            if len(members) < TEMPLATE_MIN:
                if taints is None:
                    taints = list(v.taints.items())
                for i, pod in members:
                    self._fill_pod(out, i, pod, qidx, taints)
                    for attr, names in _FIELDS_BY_ATTR:
                        if getattr(pod, attr):
                            dirty.update(names)
                continue
            tpl, was_cached = cache.get_or_build(
                self, members[0][1], fp, gen
            )
            if was_cached:
                hits += len(members)
            else:
                misses += 1
                hits += len(members) - 1
            dirty.update(tpl.direct)
            dirty.update(tpl.qidx)
            idx = np.asarray([i for i, _ in members], np.intp)
            for name, row in tpl.direct.items():
                out[name][idx] = row
            if tpl.key_seq or tpl.qidx:
                perm = np.empty(len(tpl.key_seq) + 1, np.int32)
                perm[0] = 0
                for li, key in enumerate(tpl.key_seq):
                    perm[li + 1] = qidx(key)
                for name, row in tpl.qidx.items():
                    out[name][idx] = perm[row]

        self._fill_dirty = (dirty, n, len(qidx_of) + 1)
        if hits:
            _CACHE_HITS.inc(hits)
        if misses:
            _CACHE_MISSES.inc(misses)

    def encode(self, pods: list[PodInfo]):
        t0 = time.perf_counter()
        batch = super().encode(pods)
        _ENCODE_SECONDS.inc(time.perf_counter() - t0, path=self._path)
        return batch

    def encode_packed_plain(self, cpu, mem) -> PackedPodBatch:
        t0 = time.perf_counter()
        packed = super().encode_packed_plain(cpu, mem)
        _ENCODE_SECONDS.inc(time.perf_counter() - t0, path=self._path)
        return packed

    # ---- packed encode over the arena ----------------------------------

    def _zero_view(self, name, is_bool, shape) -> np.ndarray:
        z = self._zeros.get(name)
        if z is None:
            z = np.zeros(shape, np.bool_ if is_bool else np.int32)
            z.flags.writeable = False
            self._zeros[name] = z
        return z

    def encode_packed(self, pods: list[PodInfo]) -> PackedPodBatch:
        t0 = time.perf_counter()
        specs = batch_field_specs(self.spec, self.table_spec)
        out = self._arena_take(specs)
        try:
            self._fill(out, pods)
        except BaseException:
            # A mid-fill error (oversized pod) leaves unknown regions
            # written with the dirty bookkeeping lost; drop the arena so
            # the next encode starts from fresh zeros.
            self._arena = None
            raise
        self._arena_dirty = self._fill_dirty
        # Group detection from the fill's own bookkeeping instead of 8
        # full sentinel scans: for every group but "tol", the sentinel
        # holds a True iff the attribute was nonempty iff the fill wrote
        # it (dirty).  "tolerated" alone can be written all-False (a pod
        # whose tolerations match no live taint triple — uncached
        # excludes the group then), so it keeps one real scan.
        dirty_fields = self._fill_dirty[0]
        groups = {
            g for f, g in _GROUP_OF.items()
            if g in _GROUP_SENTINEL and f == _GROUP_SENTINEL[g]
            and f != "tolerated" and f in dirty_fields
        }
        if (
            "tolerated" in dirty_fields
            and out["tolerated"][: self._fill_dirty[1]].any()
        ):
            groups.add("tol")
        if groups & {"sel", "req", "pref"}:
            groups.add("qkey")
        groups = frozenset(groups)
        # fields as views into the packed buffers: valid after the arena
        # is recycled by the next encode (CAS rollback reads them a wave
        # or more later), at zero copy cost — the buffers are fresh.
        ints, bools, fields = _pack_buffers(
            specs, groups, out, self._zero_view
        )
        _ENCODE_SECONDS.inc(time.perf_counter() - t0, path=self._path)
        return PackedPodBatch(
            ints, bools, fields, self.spec, self.table_spec, groups,
            vocab_gen=self._last_gen,
        )


def _pack_buffers(specs, groups: frozenset, out: dict, zero_view):
    """Flatten included-group field arrays into the two packed buffers
    and rebuild the field dict as views into them — the one packing body
    shared by the arena encode and the dp-shard merge."""
    int_parts, bool_parts = [], []
    for name, is_bool, _shape in specs:
        g = _GROUP_OF.get(name)
        if g is not None and g not in groups:
            continue
        (bool_parts if is_bool else int_parts).append(out[name].ravel())
    ints = (
        np.concatenate(int_parts) if int_parts else np.zeros(0, np.int32)
    )
    bools = (
        np.concatenate(bool_parts) if bool_parts else np.zeros(0, np.bool_)
    )
    fields: dict[str, np.ndarray] = {}
    io = bo = 0
    for name, is_bool, shape in specs:
        g = _GROUP_OF.get(name)
        if g is not None and g not in groups:
            fields[name] = zero_view(name, is_bool, shape)
            continue
        size = math.prod(shape)
        if is_bool:
            fields[name] = bools[bo : bo + size].reshape(shape)
            bo += size
        else:
            fields[name] = ints[io : io + size].reshape(shape)
            io += size
    return ints, bools, fields


# Read-only zeros for merge_packed's excluded groups (the standalone
# counterpart of HotPodBatchHost._zero_view; shapes are spec-derived so
# the cache stays tiny).
_MERGE_ZEROS: dict = {}


def _merge_zero_view(name, is_bool, shape) -> np.ndarray:
    z = _MERGE_ZEROS.get((name, shape))
    if z is None:
        z = np.zeros(shape, np.bool_ if is_bool else np.int32)
        z.flags.writeable = False
        _MERGE_ZEROS[(name, shape)] = z
    return z


def merge_packed(parts: list[PackedPodBatch]) -> PackedPodBatch | None:
    """Concatenate dp contiguous sub-batches into one full-batch
    ``PackedPodBatch``, or None when they cannot merge (merged query
    keys overflow ``PodSpec.query_keys``, or the parts were encoded
    against different vocab generations).

    Byte-identity: each sub-batch's per-batch query-key table lists its
    distinct selector keys in first-encounter order, and a key's first
    reference across the FULL batch always happens in the earliest
    sub-batch referencing it — so replaying the sub-tables in dp order
    rebuilds exactly the slot assignment a single full-batch encode
    produces, and the merged buffers are byte-identical to encoding the
    concatenated pod list inline (tests/test_mesh_differential.py).
    The one exception: a never-interned selector key encodes as NONE_ID,
    which two sub-batches cannot distinguish from each other's unknown
    keys — those slots merge by id, which the device cannot tell apart
    either (both query an id no node carries).
    """
    first = parts[0]
    b_total = sum(p.spec.batch for p in parts)
    mspec = dataclasses.replace(first.spec, batch=b_total)
    for p in parts[1:]:
        if (
            dataclasses.replace(p.spec, batch=0)
            != dataclasses.replace(first.spec, batch=0)
            or p.table_spec != first.table_spec
        ):
            raise ValueError("merge_packed parts disagree on specs")
    gens = {p.vocab_gen for p in parts if p.vocab_gen is not None}
    if len(gens) > 1:
        return None
    groups = frozenset().union(*(p.groups for p in parts))

    # Merged query-key table + per-part slot permutations (slot 0 stays
    # the reserved NONE slot everywhere).
    qkey = np.zeros((mspec.query_keys,), np.int32)
    slot_of: dict[int, int] = {}
    next_slot = 1
    perms = []
    for p in parts:
        used = 0
        for name in _QIDX_FIELDS:
            if _GROUP_OF[name] in p.groups:
                used = max(used, int(p.fields[name].max()))
        perm = np.zeros((used + 1,), np.int32)
        tbl = p.fields["qkey"]
        for local in range(1, used + 1):
            kid = int(tbl[local])
            slot = slot_of.get(kid) if kid != 0 else None
            if slot is None:
                if next_slot >= mspec.query_keys:
                    return None      # caller falls back to inline encode
                slot = next_slot
                next_slot += 1
                qkey[slot] = kid
                if kid != 0:
                    slot_of[kid] = slot
            perm[local] = slot
        perms.append(perm)

    specs = batch_field_specs(mspec, first.table_spec)
    merged: dict[str, np.ndarray] = {}
    for name, is_bool, shape in specs:
        g = _GROUP_OF.get(name)
        if g is not None and g not in groups:
            continue
        if name == "qkey":
            merged[name] = qkey
        elif name in _QIDX_FIELDS:
            merged[name] = np.concatenate(
                [perm[p.fields[name]] for perm, p in zip(perms, parts)]
            )
        else:
            merged[name] = np.concatenate([p.fields[name] for p in parts])
    ints, bools, fields = _pack_buffers(
        specs, groups, merged, _merge_zero_view
    )
    return PackedPodBatch(
        ints, bools, fields, mspec, first.table_spec, groups,
        vocab_gen=gens.pop() if gens else None,
    )


def encode_batch(enc: PodBatchHost, batch_pods, *, mutate: bool = True):
    """Encode popped/peeked PendingPods with ``enc`` — the ONE encode
    body both the inline path (Coordinator._take_batch) and the feed
    worker run, so staged and inline encodes of the same pods can never
    drift.  ``mutate=False`` (the worker) materializes missing PodInfos
    without assigning ``p.pod`` — the peeked objects still belong to
    the cycle thread's queue."""
    # graftlint: disable=hotfeed-no-per-pod-python (O(pods) scalar extraction feeding the vectorized plain lane / cached fill)
    if all(p.pod is None for p in batch_pods):
        # Native-intake fast lane: a wave of plain pods encodes from
        # two int columns, no per-pod Python (vocab-independent, so the
        # stamp stays None and claim() skips the generation check).
        return enc.encode_packed_plain(
            [p.cpu_milli for p in batch_pods],  # graftlint: disable=hotfeed-no-per-pod-python (scalar column)
            [p.mem_kib for p in batch_pods],  # graftlint: disable=hotfeed-no-per-pod-python (scalar column)
        )
    if mutate:
        # graftlint: disable=hotfeed-no-per-pod-python (materializing PodInfo refs for the cached fill; field writes are vectorized inside)
        return enc.encode_packed([p.ensure_pod() for p in batch_pods])
    # graftlint: disable=hotfeed-no-per-pod-python (read-only PodInfo materialization for the worker)
    return enc.encode_packed([p.peek_pod() for p in batch_pods])


@guarded_by(_req="_lock", _staged="_lock", _closed="_lock")
class HostFeed:
    """Double-buffered host feed: one worker thread encodes the next
    wave's batch while the current wave is in flight.

    Protocol (cycle thread):

    - ``stage(queue, batch)`` after a dispatch: PEEKS (never pops) the
      first ``batch`` pods and hands the list to the worker.  Only full
      batches stage — partial waves are the light-load latency path,
      where adaptive buckets pick the encoder and inline encode is
      already cheap; staging them would freeze a too-small batch while
      the queue refills behind it.
    - ``claim(batch_pods, generation)`` at the next dispatch: waits out
      any in-progress encode (always shorter than encoding inline —
      the work is part-done), then returns the staged PackedPodBatch
      iff (a) the popped pods are exactly the peeked prefix, same
      objects in the same order, and (b) the vocab generation has not
      moved since the encode.  Anything else returns None and the
      caller encodes inline; `hotfeed_stale_batches_total{reason}`
      counts why.

    The worker owns a dedicated encoder (its arena never races the
    cycle thread's inline/adjust encoders); claim()'s wait guarantees
    the worker is idle before the next stage().  A worker that raises
    stages ``None`` — the inline fallback then reproduces any real
    encode error on the cycle thread, where it can propagate.
    """

    def __init__(self, encoder: HotPodBatchHost, name: str = "hotfeed"):
        self.encoder = encoder
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._req: list | None = None
        self._staged: tuple | None = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()
        with _FEEDS_LOCK:
            _LIVE_FEEDS.add(self)

    def depth(self) -> int:
        with self._lock:
            return (self._req is not None) + (self._staged is not None)

    def ready(self) -> bool:
        """A staged batch is waiting (the worker finished encoding)."""
        with self._lock:
            return self._staged is not None

    def stage(self, queue, batch: int) -> bool:
        """Peek the first ``batch`` pods off ``queue`` (a deque the
        caller owns) and submit them for background encode.  No-op
        unless a full batch is available and the feed is idle."""
        if len(queue) < batch:
            return False
        return self.stage_pods(list(itertools.islice(queue, batch)))

    def stage_pods(self, pods: list) -> bool:
        """Submit an already-peeked pod list for background encode (the
        sharded feed's per-dp-slice entry point).  The list must remain
        a queue prefix snapshot — claim()'s identity check enforces it."""
        with self._lock:
            if (
                self._closed
                or self._req is not None or self._staged is not None
            ):
                return False
            self._req = pods
            self._cond.notify_all()
        return True

    def claim(self, batch_pods: list, generation: int):
        """The staged PackedPodBatch for exactly ``batch_pods`` at
        ``generation``, or None (caller encodes inline)."""
        deadline = time.monotonic() + 60.0
        with self._lock:
            while self._req is not None:
                # The worker always finishes (pure numpy, no I/O); the
                # deadline is a liveness backstop — a wedged worker
                # degrades to inline encodes (its eventual stale result
                # is discarded by the prefix check on a later claim).
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    log.error("hotfeed worker unresponsive; encoding inline")
                    return None
                self._cond.wait(timeout=remaining)
            staged, self._staged = self._staged, None
        if staged is None:
            return None
        pods, packed = staged
        if packed is None:
            _STALE.inc(reason="error")
            return None
        # graftlint: disable=hotfeed-no-per-pod-python (O(pods) identity compare deciding whether the staged bytes are usable at all)
        if len(pods) != len(batch_pods) or any(
            a is not b for a, b in zip(pods, batch_pods)
        ):
            # The queue prefix changed between peek and pop (requeue,
            # breaker pops, resync churn): the staged bytes describe
            # pods this wave is not carrying.
            _STALE.inc(reason="reordered")
            return None
        if packed.vocab_gen is not None and packed.vocab_gen != generation:
            # Interning moved between staging and dispatch — the cached
            # template ids may predate taints/labels this wave must see.
            _STALE.inc(reason="vocab")
            return None
        _STAGED_USED.inc()
        return packed

    def _run(self) -> None:
        while True:
            with self._lock:
                while self._req is None and not self._closed:
                    self._cond.wait()
                if self._closed:
                    return
                pods = self._req
            try:
                # mutate=False: the peeked PendingPods are still owned
                # by the cycle thread's queue; the worker must not
                # assign p.pod (the one write ensure_pod would do).
                packed = encode_batch(self.encoder, pods, mutate=False)
            # Broad on purpose (log.exception satisfies the lint): the
            # worker must stage None so the inline fallback reproduces
            # the error on the cycle thread, where it can propagate.
            except Exception:
                log.exception("hotfeed worker encode failed; staging None")
                packed = None
            with self._lock:
                self._staged = (pods, packed)
                self._req = None
                self._cond.notify_all()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)


class ShardedHostFeed:
    """One ``HostFeed`` per dp shard: the mesh coordinator's overlapped
    encode, parallelized the same way the device work is.

    A (dp, sp) mesh shards the pod batch over dp; the host encode was
    still one serial worker filling the whole wave.  This feed peeks the
    same full-batch queue prefix, splits it into dp contiguous slices,
    and lets dp workers (one per shard, each with its own arena, all
    sharing the coordinator's EncodeCache) encode concurrently; claim
    verifies every slice exactly like the single feed (prefix identity +
    vocab generation, fail closed) and merges the sub-batches into one
    full-batch ``PackedPodBatch`` byte-identical to the inline encode
    (merge_packed).  A merge that cannot be trusted — query-key overflow
    across slices, mixed generations — counts
    ``hotfeed_stale_batches_total{reason="merge"}`` and the caller
    encodes inline, the same fail-closed contract as the single feed.

    No own locked state: the sub-feeds carry the ``@guarded_by``
    discipline, and this wrapper only ever runs on the cycle thread.
    """

    def __init__(self, encoders: list[HotPodBatchHost], name: str = "hotfeed"):
        if not encoders:
            raise ValueError("ShardedHostFeed needs >= 1 encoder")
        self._b_local = encoders[0].spec.batch
        self.feeds = [
            HostFeed(enc, name=f"{name}-dp{i}")
            for i, enc in enumerate(encoders)
        ]

    def depth(self) -> int:
        return sum(f.depth() for f in self.feeds)

    def depths(self) -> list[int]:
        """Per-dp-shard staged depth (sched_bench's mesh report)."""
        return [f.depth() for f in self.feeds]

    def ready(self) -> bool:
        return all(f.ready() for f in self.feeds)

    def stage(self, queue, batch: int) -> bool:
        if batch != self._b_local * len(self.feeds) or len(queue) < batch:
            return False
        if any(f.depth() for f in self.feeds):
            return False
        peeked = list(itertools.islice(queue, batch))
        b = self._b_local
        for i, f in enumerate(self.feeds):
            f.stage_pods(peeked[i * b : (i + 1) * b])
        return True

    def claim(self, batch_pods: list, generation: int):
        """The merged staged batch for exactly ``batch_pods``, or None.
        Every sub-feed is claimed regardless (staged state must drain
        even when one slice went stale, or the feeds would wedge)."""
        b = self._b_local
        parts = [
            f.claim(batch_pods[i * b : (i + 1) * b], generation)
            for i, f in enumerate(self.feeds)
        ]
        if any(p is None for p in parts):
            return None
        merged = merge_packed(parts)
        if merged is None:
            _STALE.inc(reason="merge")
            return None
        return merged

    def close(self) -> None:
        for f in self.feeds:
            f.close()
