"""Host-side string interning.

Labels, taints and selector values are strings in Kubernetes but the TPU
only sees integer ids.  The Interner is the boundary: every string that can
appear in a filter/score decision is mapped to a stable int32 id on the
host, once, at snapshot-delta-apply time.  The device never recompiles when
new strings appear — ids are data, not shapes.

The reference does the same thing implicitly: its Go scheduler caches parse
label strings into map keys per informer event; here the parse happens once
per string ever seen (reference cmd/dist-scheduler/leader_activities.go:112-172
strips fields to shrink that cache; our equivalent is this table).
"""

from __future__ import annotations

from k8s1m_tpu.config import NO_NUMERIC, NONE_ID


class Interner:
    """Bidirectional value<->int table over hashables. Id 0 is "absent"."""

    def __init__(self) -> None:
        self._to_id: dict = {}
        self._to_val: list = [None]

    def intern(self, s) -> int:
        if s is None:
            return NONE_ID
        i = self._to_id.get(s)
        if i is None:
            i = len(self._to_val)
            self._to_id[s] = i
            self._to_val.append(s)
        return i

    def lookup(self, s) -> int:
        """Like intern, but returns NONE_ID for never-seen values.

        Used when encoding *queries* (pod selectors): a value that was never
        interned cannot match any node, and must not grow the table.
        """
        if s is None:
            return NONE_ID
        return self._to_id.get(s, NONE_ID)

    def value(self, i: int):
        return self._to_val[i]

    # Kept for readability at string-namespace call sites.
    string = value

    def __len__(self) -> int:
        return len(self._to_val)

    def __contains__(self, s) -> bool:
        return s in self._to_id

    def items(self):
        """Yields (id, value) for every interned value (skips the 0 slot)."""
        for i in range(1, len(self._to_val)):
            yield i, self._to_val[i]


# ASCII lead characters that make ``int(value, 10)`` unconditionally
# raise: everything printable except sign, digit, and the whitespace
# int() strips.  The common non-numeric label value ("kwok", a zone
# name, a hostname) short-circuits on one set probe instead of paying
# the ~1us exception unwind — at 1M nodes x label_slots that unwind was
# a measurable slice of the cold-build wall.  Non-ASCII leads (unicode
# whitespace is stripped by int()) still take the exact try path.
_NONNUM_LEAD = frozenset(
    c for c in map(chr, range(33, 127)) if c not in "+-0123456789"
)


def numeric_of(value: str) -> int:
    """Integer value of a label for Gt/Lt selector ops, or NO_NUMERIC.

    Upstream parses the node label with strconv.ParseInt; non-integers make
    Gt/Lt requirements unsatisfiable.
    """
    if isinstance(value, str) and value and value[0] in _NONNUM_LEAD:
        return NO_NUMERIC
    try:
        return int(value, 10)
    except (ValueError, TypeError):
        # graftlint: disable=fallback-counts-or-raises (NO_NUMERIC is the defined value for non-integer labels — upstream ParseInt semantics, not a degradation; a per-label metric would tax the cold-build hot path)
        return NO_NUMERIC


class Vocab:
    """The full interning state shared by a snapshot.

    Separate namespaces so e.g. a taint key and a label value never collide
    into a false match.
    """

    def __init__(self) -> None:
        self.label_keys = Interner()
        self.label_values = Interner()
        # Whole (key, value, effect) taint triples.  The toleration check is
        # evaluated host-side once per (pod, distinct triple) and shipped to
        # the device as a bitmask — the cluster-wide distinct-taint count is
        # tiny even at 1M nodes.
        self.taints = Interner()
        self.node_names = Interner()
        self.zones = Interner()
        self.regions = Interner()

    def generation(self) -> int:
        """Monotonic counter over the *encode-relevant* namespaces.

        The hotfeed encode cache (snapshot/hotfeed.py) keys its templates
        on this: a cached ``tolerated`` row bakes in the taint triples
        interned at encode time, and cached selector value ids bake in
        ``label_values`` lookups (a value unseen then encodes NONE_ID but
        would encode a real id after a node introduces it).  Interners
        only grow, so the sum of lengths is a valid generation counter.

        ``node_names`` / ``zones`` / ``regions`` are deliberately
        EXCLUDED: ``spec.nodeName`` is resolved per pod at fill time (a
        scalar column, never cached in a template), so node churn — the
        high-rate namespace — must not invalidate the shape cache.
        """
        return len(self.label_keys) + len(self.label_values) + len(self.taints)

    def feed_generation(self) -> int:
        """Staleness stamp for a fully-ENCODED batch — ``generation()``
        plus the node-name namespace.  A batch's scalar ``node_name_id``
        column bakes ``node_names`` lookups (a ``spec.nodeName`` naming
        a then-unknown node encodes the -1 never-matches sentinel, but
        would resolve once the node interns), so the hotfeed's staged
        batches must also go stale on node-name growth — unlike the
        template cache, whose rows never contain node-name ids.
        """
        return self.generation() + len(self.node_names)
