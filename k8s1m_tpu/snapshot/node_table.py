"""The HBM-resident node table: a struct-of-arrays snapshot of every node.

This replaces the reference's 256 label-sharded Go informer caches
(reference cmd/dist-scheduler/scheduler.go:201-219, ~100KB/node in RAM per
RUNNING.adoc:193) with one padded tensor table: ~250 bytes/node, so a
million nodes is ~250MB — a fraction of one chip's HBM.  The table is a JAX
pytree; sharding it over the mesh's node axis is the TPU equivalent of the
reference's `dist-scheduler.dev/scheduler` label sharding
(reference cmd/dist-scheduler/leader_activities.go:227-343).

Mutation happens two ways, both jit-compatible scatters:
- ``apply_delta``   — coordinator-streamed node add/update/remove, the
  equivalent of informer events (revision-ordered by the coordinator the
  way mem_etcd's notify thread orders watch events, reference
  mem_etcd/src/store.rs:444-533).
- ``commit_binds``  — the engine folds its own bind decisions back into
  requested-resources before the next batch, the equivalent of the
  scheduler's assume/bind cache update.

**Wave epochs & the free-row quarantine.**  A pipelined coordinator keeps
several device waves in flight; a wave launched before a node removal may
still hold the removed row in its candidate lists.  Freeing the row id
immediately would let the next node allocation reuse it, and the in-flight
wave's bind would silently land on the *new* node (row aliasing).  So row
removal is two-phase: ``remove`` tombstones the row (``valid=0`` in the
host mirror; the coordinator scatters it to the device the same cycle)
and parks the row id in a quarantine stamped with the current
``wave_epoch`` — the count of waves launched so far (``begin_wave``).
``release_rows(before_epoch)`` returns quarantined rows to the free list
once every wave launched at or before their removal epoch has retired.
Fresh-row allocation appends past the high-water mark (or reuses a
*released* row), so structural adds never need the pipeline quiesced;
only quarantine exhaustion (``RowsExhausted`` with rows parked) does.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from k8s1m_tpu.config import (
    EFFECT_NO_SCHEDULE,
    NONE_ID,
    TableSpec,
)
from k8s1m_tpu.lint import THREAD_OWNER, guarded_by
from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.snapshot.interning import Vocab, numeric_of

_BULK_ROWS = Counter(
    "megarow_bulk_ingest_rows_total",
    "Node rows ingested through the vectorized bulk lane "
    "(NodeTableHost.bulk_upsert / snapshot.bulkload) — rate vs wall "
    "clock is the bulk-ingest rows/s evidence", (),
)
_MIRROR_BYTES = Gauge(
    "megarow_host_mirror_bytes",
    "Host-mirror column bytes across live NodeTableHost instances "
    "(the int16/int8 mirror-width rule's budget gauge)", (),
)
_LIVE_HOSTS: weakref.WeakSet = weakref.WeakSet()
# The gauge callback runs on the metrics scrape thread while any other
# thread may be constructing a NodeTableHost; a bare WeakSet iteration
# concurrent with add() raises "set changed size during iteration", so
# both sides serialize on this lock (mirror_nbytes reads immutable
# array headers — cheap enough to hold it across the sum).
_HOSTS_LOCK = threading.Lock()


def _mirror_bytes_total() -> int:
    with _HOSTS_LOCK:
        return sum(h.mirror_nbytes() for h in _LIVE_HOSTS)


_MIRROR_BYTES.set_function(_mirror_bytes_total)


def mirror_dtype(bound: int) -> np.dtype:
    """Host-mirror column width for ids in ``[0, bound)``: the
    narrowest signed dtype that holds the TableSpec bound, mirroring
    snapshot/packing.py's packed-layout dtype decisions.  A million-row
    mirror must not spend 4 bytes on a 512-value zone column; the
    device-facing transfer paths (``to_device``, the coordinator's
    dirty-row deltas) re-widen to the canonical int32 so the unpacked
    device layout is byte-identical either way.  New columns MUST pick
    their width through this rule (MIGRATION: "Host-mirror dtypes")."""
    if bound <= 1 << 7:
        return np.dtype(np.int8)
    if bound <= 1 << 15:
        return np.dtype(np.int16)
    return np.dtype(np.int32)

class RowsExhausted(ValueError):
    """No allocatable row: the table is at ``max_nodes`` and the free
    list is empty.  ``quarantined`` says how many rows are parked in the
    wave-epoch quarantine — nonzero means a pipeline quiesce (retire all
    in-flight waves, then ``release_rows(None)``) recovers capacity;
    zero means the table is genuinely full (re-bucket TableSpec)."""

    def __init__(self, msg: str, quarantined: int = 0):
        super().__init__(msg)
        self.quarantined = quarantined


UNSCHEDULABLE_TAINT_KEY = "node.kubernetes.io/unschedulable"
ZONE_LABEL = "topology.kubernetes.io/zone"
REGION_LABEL = "topology.kubernetes.io/region"
HOSTNAME_LABEL = "kubernetes.io/hostname"


@dataclasses.dataclass
class Taint:
    key: str
    value: str = ""
    effect: int = EFFECT_NO_SCHEDULE


@dataclasses.dataclass
class NodeInfo:
    """Host-side description of one node (the parsed KWOK/real Node object)."""

    name: str
    cpu_milli: int = 4000
    mem_kib: int = 8 << 20          # 8 GiB
    pods: int = 110
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    taints: list[Taint] = dataclasses.field(default_factory=list)
    unschedulable: bool = False


@struct.dataclass
class NodeTable:
    """Device-resident snapshot. All arrays padded to spec.max_nodes rows."""

    valid: jax.Array        # bool[N] — row is a live node
    # Allocatable (reference: node.status.allocatable).
    cpu_alloc: jax.Array    # i32[N] milliCPU
    mem_alloc: jax.Array    # i32[N] KiB  (2 TiB/node cap; KWOK nodes are far below)
    pods_alloc: jax.Array   # i32[N]
    # Sum of requests of pods assumed/bound to the node.
    cpu_req: jax.Array      # i32[N]
    mem_req: jax.Array      # i32[N]
    pods_req: jax.Array     # i32[N]
    # Interned labels: padded (key,value) slots + pre-parsed numeric value
    # for Gt/Lt selector operators.
    label_key: jax.Array    # i32[N, L]
    label_val: jax.Array    # i32[N, L]
    label_num: jax.Array    # i32[N, L]
    # Taints as interned (key,value,effect)-triple ids plus the effect, so
    # the filter can distinguish hard (NoSchedule/NoExecute) from soft
    # (PreferNoSchedule) without re-deriving it.  node.spec.unschedulable is
    # folded in as the canonical node.kubernetes.io/unschedulable:NoSchedule
    # taint (upstream TaintNodeUnschedulable).
    taint_id: jax.Array     # i32[N, T] triple id in [0, max_taint_ids)
    taint_effect: jax.Array  # i32[N, T]
    # Dense topology-domain ids for the count tables.
    zone: jax.Array         # i32[N] in [0, max_zones)
    region: jax.Array       # i32[N] in [0, max_regions)
    name_id: jax.Array      # i32[N] interned node name (NodeName plugin)

    @property
    def num_rows(self) -> int:
        return self.valid.shape[0]

    def free(self):
        """(cpu, mem, pods) still unrequested, for Fit and LeastAllocated."""
        return (
            self.cpu_alloc - self.cpu_req,
            self.mem_alloc - self.mem_req,
            self.pods_alloc - self.pods_req,
        )


def empty_table(spec: TableSpec) -> NodeTable:
    n, l, t = spec.max_nodes, spec.label_slots, spec.taint_slots
    i32 = jnp.int32
    return NodeTable(
        valid=jnp.zeros((n,), jnp.bool_),
        cpu_alloc=jnp.zeros((n,), i32),
        mem_alloc=jnp.zeros((n,), i32),
        pods_alloc=jnp.zeros((n,), i32),
        cpu_req=jnp.zeros((n,), i32),
        mem_req=jnp.zeros((n,), i32),
        pods_req=jnp.zeros((n,), i32),
        label_key=jnp.zeros((n, l), i32),
        label_val=jnp.zeros((n, l), i32),
        label_num=jnp.zeros((n, l), i32),
        taint_id=jnp.zeros((n, t), i32),
        taint_effect=jnp.zeros((n, t), i32),
        zone=jnp.zeros((n,), i32),
        region=jnp.zeros((n,), i32),
        name_id=jnp.zeros((n,), i32),
    )


@guarded_by(
    # The wave-epoch quarantine and the row mapping are the no-aliasing
    # core of quiesce-free pipelining (PR 3): both are cycle-thread-
    # confined, and a foreign thread touching either could hand an
    # in-flight wave's row to a new node.  Audited under
    # lint/guards.py's instrumentation mode.
    _quarantine=THREAD_OWNER,
    _free_rows=THREAD_OWNER,
    _row_of=THREAD_OWNER,
    wave_epoch=THREAD_OWNER,
)
class NodeTableHost:
    """Host-side builder/mirror of the node table (numpy, mutable).

    The coordinator owns one of these: informer-style deltas mutate it and
    are batched into device scatters.  It is also the feature compiler —
    the only place node strings are parsed and interned.
    """

    def __init__(self, spec: TableSpec, vocab: Vocab | None = None) -> None:
        self.spec = spec
        self.vocab = vocab or Vocab()
        n, l, t = spec.max_nodes, spec.label_slots, spec.taint_slots
        self.valid = np.zeros((n,), np.bool_)
        self.cpu_alloc = np.zeros((n,), np.int32)
        self.mem_alloc = np.zeros((n,), np.int32)
        self.pods_alloc = np.zeros((n,), np.int32)
        self.cpu_req = np.zeros((n,), np.int32)
        self.mem_req = np.zeros((n,), np.int32)
        self.pods_req = np.zeros((n,), np.int32)
        # Label/name ids are unbounded by TableSpec (a 1M-node cluster
        # interns ~1M hostname label values), so they stay int32; the
        # spec-bounded columns take the narrow mirror width.
        self.label_key = np.zeros((n, l), np.int32)
        self.label_val = np.zeros((n, l), np.int32)
        self.label_num = np.zeros((n, l), np.int32)
        self.taint_id = np.zeros((n, t), mirror_dtype(spec.max_taint_ids))
        # Effects are the 2-bit EFFECT_* range, checked at upsert the
        # same way pack_meta_np fail-closes past the packed budget.
        self.taint_effect = np.zeros((n, t), np.int8)
        self.zone = np.zeros((n,), mirror_dtype(spec.max_zones))
        self.region = np.zeros((n,), mirror_dtype(spec.max_regions))
        self.name_id = np.zeros((n,), np.int32)
        self._row_of: dict[str, int] = {}
        self._free_rows: list[int] = []
        self._next_row = 0
        # Pipelined-scheduler wave clock: bumped by begin_wave() at every
        # device dispatch.  0 = no consumer pipelines waves, and removes
        # free their row immediately (standalone/tool users of this
        # class never quarantine).
        self.wave_epoch = 0
        # Removed rows awaiting release: (removal wave_epoch, row),
        # epoch-ordered by construction (the clock is monotone).  A row
        # here is tombstoned (valid=0, columns zeroed) but NOT reusable —
        # a wave launched before the removal may still bind into it.
        self._quarantine: collections.deque[tuple[int, int]] = (
            collections.deque()
        )
        # Bumped on every row->name mapping change (new node, removal,
        # row reuse) — consumers holding derived per-row state (the shard
        # set's ownership mask) refresh when this moves.
        self.epoch = 0
        # Opt-in delta journal of those changes: (name, row, alive)
        # appended in order, so a consumer can update per-row state
        # incrementally instead of re-scanning 1M rows per change.  The
        # consumer owns draining it (enable_row_journal returns the list;
        # clear after consuming).
        self._row_journal: list[tuple[str, int, bool]] | None = None
        with _HOSTS_LOCK:
            _LIVE_HOSTS.add(self)

    def mirror_nbytes(self) -> int:
        """Total bytes held by the mirror's column arrays (the
        megarow_host_mirror_bytes evidence; excludes the row mapping
        and vocab, which are Python dicts)."""
        return sum(
            getattr(self, c).nbytes
            for c in (
                "valid", "cpu_alloc", "mem_alloc", "pods_alloc",
                "cpu_req", "mem_req", "pods_req",
                "label_key", "label_val", "label_num",
                "taint_id", "taint_effect", "zone", "region", "name_id",
            )
        )

    def enable_row_journal(self) -> list[tuple[str, int, bool]]:
        if self._row_journal is None:
            self._row_journal = []
        return self._row_journal

    # ---- row management -------------------------------------------------

    def row_of(self, name: str) -> int:
        return self._row_of[name]

    def _alloc_row(self, name: str) -> int:
        if name in self._row_of:
            return self._row_of[name]
        if self._free_rows:
            row = self._free_rows.pop()
        else:
            row = self._next_row
            if row >= self.spec.max_nodes:
                raise RowsExhausted(
                    f"node table full ({self.spec.max_nodes}); re-bucket "
                    "TableSpec" + (
                        f" ({len(self._quarantine)} rows quarantined; a "
                        "pipeline quiesce releases them)"
                        if self._quarantine else ""
                    ),
                    quarantined=len(self._quarantine),
                )
            self._next_row += 1
        self._row_of[name] = row
        self.epoch += 1
        if self._row_journal is not None:
            self._row_journal.append((name, row, True))
        return row

    def bulk_alloc(self, names) -> np.ndarray:
        """Allocate (or resolve) a row per name, with the capacity
        check front-loaded: either every name gets a row, or
        RowsExhausted raises BEFORE any allocation — a mid-batch raise
        would leave names mapped to rows whose columns were never
        written (the bulk lanes write columns only after every row is
        allocated)."""
        row_of = self._row_of
        fresh = {n for n in names if n not in row_of}
        free = len(self._free_rows) + (self.spec.max_nodes - self._next_row)
        if len(fresh) > free:
            raise RowsExhausted(
                f"bulk ingest needs {len(fresh)} fresh rows but only "
                f"{free} are allocatable (max_nodes="
                f"{self.spec.max_nodes})" + (
                    f" ({len(self._quarantine)} rows quarantined; a "
                    "pipeline quiesce releases them)"
                    if self._quarantine else ""
                ),
                quarantined=len(self._quarantine),
            )
        rows = np.empty((len(names),), np.int64)
        alloc = self._alloc_row
        for i, name in enumerate(names):
            rows[i] = alloc(name)
        return rows

    def alloc_rows(self, names: list[str]) -> np.ndarray:
        """Bulk-allocate contiguous-ish rows for many new nodes.

        Fast path for load generators (the make_nodes equivalent,
        reference kwok/make_nodes/main.go:116-182): callers fill the table
        columns vectorized; per-row python dispatch would dominate at 1M.
        """
        rows = np.empty((len(names),), np.int64)
        for i, name in enumerate(names):
            rows[i] = self._alloc_row(name)
        self.valid[rows] = True
        return rows

    # ---- deltas ---------------------------------------------------------

    def upsert(self, node: NodeInfo) -> int:
        """Add or update a node; returns its row."""
        v = self.vocab
        row = self._alloc_row(node.name)

        labels = dict(node.labels)
        labels.setdefault(HOSTNAME_LABEL, node.name)
        if len(labels) > self.spec.label_slots:
            raise ValueError(
                f"node {node.name}: {len(labels)} labels > "
                f"label_slots={self.spec.label_slots}"
            )
        lk = np.zeros((self.spec.label_slots,), np.int32)
        lv = np.zeros_like(lk)
        ln = np.zeros_like(lk)
        for i, (k, val) in enumerate(sorted(labels.items())):
            lk[i] = v.label_keys.intern(k)
            lv[i] = v.label_values.intern(val)
            ln[i] = numeric_of(val)

        taints = list(node.taints)
        if node.unschedulable:
            taints.append(Taint(UNSCHEDULABLE_TAINT_KEY, "", EFFECT_NO_SCHEDULE))
        if len(taints) > self.spec.taint_slots:
            raise ValueError(
                f"node {node.name}: {len(taints)} taints > "
                f"taint_slots={self.spec.taint_slots}"
            )
        tk = np.zeros((self.spec.taint_slots,), self.taint_id.dtype)
        te = np.zeros((self.spec.taint_slots,), self.taint_effect.dtype)
        for i, taint in enumerate(taints):
            if not 0 <= taint.effect < 4:
                # Same fail-closed contract as pack_meta_np's 2-bit
                # budget: an out-of-range effect must raise here, not
                # truncate into the int8 mirror.
                raise ValueError(
                    f"node {node.name}: taint effect {taint.effect} "
                    "outside the EFFECT_* range [0, 4)"
                )
            tid = v.taints.intern((taint.key, taint.value, taint.effect))
            if tid >= self.spec.max_taint_ids:
                raise ValueError(
                    "distinct taint triples overflow TableSpec.max_taint_ids"
                )
            tk[i] = tid
            te[i] = taint.effect

        zone_id = v.zones.intern(labels.get(ZONE_LABEL)) if ZONE_LABEL in labels else NONE_ID
        region_id = (
            v.regions.intern(labels.get(REGION_LABEL)) if REGION_LABEL in labels else NONE_ID
        )
        if zone_id >= self.spec.max_zones or region_id >= self.spec.max_regions:
            raise ValueError("zone/region id overflow; grow TableSpec.max_zones/max_regions")

        self.valid[row] = True
        self.cpu_alloc[row] = node.cpu_milli
        self.mem_alloc[row] = node.mem_kib
        self.pods_alloc[row] = node.pods
        self.label_key[row], self.label_val[row], self.label_num[row] = lk, lv, ln
        self.taint_id[row], self.taint_effect[row] = tk, te
        self.zone[row] = zone_id
        self.region[row] = region_id
        self.name_id[row] = v.node_names.intern(node.name)
        return row

    def bulk_upsert(self, nodes) -> np.ndarray:
        """Vectorized ``upsert`` over many nodes; returns their rows.

        Byte-identical to ``[self.upsert(n) for n in nodes]`` — same
        column bytes, same row mapping, same vocab contents in the same
        intern order, same row-journal entries — but the per-node numpy
        allocations and scattered row writes collapse into block fills
        and one fancy-indexed write per column, the first wall a 1M-row
        cold build hits (ISSUE 14).  A name repeated within the batch
        resolves like repeated upserts: the later entry wins (numpy
        fancy assignment applies in order).

        Validation is front-loaded: any per-node error (label/taint
        overflow, id past a TableSpec bound) raises BEFORE any table
        column, row mapping or journal mutation — strictly cleaner than
        the loop's partial application (interned strings from the batch
        may remain; interners are append-only and ids are data).
        """
        spec = self.spec
        v = self.vocab
        b, nslots, tslots = len(nodes), spec.label_slots, spec.taint_slots
        lk = np.zeros((b, nslots), np.int32)
        lv = np.zeros((b, nslots), np.int32)
        ln = np.zeros((b, nslots), np.int32)
        tk = np.zeros((b, tslots), self.taint_id.dtype)
        te = np.zeros((b, tslots), self.taint_effect.dtype)
        zone = np.zeros((b,), np.int32)
        region = np.zeros((b,), np.int32)
        name_id = np.zeros((b,), np.int32)
        cpu = np.zeros((b,), np.int32)
        mem = np.zeros((b,), np.int32)
        pods = np.zeros((b,), np.int32)
        # Interner internals bound once per batch: the per-label
        # ``intern`` method-call overhead is a measured slice of the 1M
        # ingest wall (same-package access, mirrors Interner.intern).
        lk_id, lk_val = v.label_keys._to_id, v.label_keys._to_val
        lv_id, lv_val = v.label_values._to_id, v.label_values._to_val
        # numeric_of memo keyed by interned value id: repeated label
        # values (zones, groups) pay the parse once per distinct value.
        num_of: dict[int, int] = {}
        for i, node in enumerate(nodes):
            labels = dict(node.labels)
            labels.setdefault(HOSTNAME_LABEL, node.name)
            if len(labels) > nslots:
                raise ValueError(
                    f"node {node.name}: {len(labels)} labels > "
                    f"label_slots={nslots}"
                )
            for j, (k, val) in enumerate(sorted(labels.items())):
                ik = lk_id.get(k)
                if ik is None:
                    ik = len(lk_val)
                    lk_id[k] = ik
                    lk_val.append(k)
                if val is None:
                    # Interner.intern's None -> NONE_ID mapping (a JSON
                    # null label value reaches here via decode_node);
                    # the inlined fast path must not intern None as a
                    # fresh id or the bulk lane diverges from upsert.
                    iv, num = NONE_ID, numeric_of(val)
                else:
                    iv = lv_id.get(val)
                    if iv is None:
                        iv = len(lv_val)
                        lv_id[val] = iv
                        lv_val.append(val)
                    num = num_of.get(iv)
                    if num is None:
                        num = numeric_of(val)
                        num_of[iv] = num
                lk[i, j] = ik
                lv[i, j] = iv
                ln[i, j] = num
            taints = list(node.taints)
            if node.unschedulable:
                taints.append(
                    Taint(UNSCHEDULABLE_TAINT_KEY, "", EFFECT_NO_SCHEDULE)
                )
            if len(taints) > tslots:
                raise ValueError(
                    f"node {node.name}: {len(taints)} taints > "
                    f"taint_slots={tslots}"
                )
            for j, taint in enumerate(taints):
                if not 0 <= taint.effect < 4:
                    raise ValueError(
                        f"node {node.name}: taint effect {taint.effect} "
                        "outside the EFFECT_* range [0, 4)"
                    )
                tid = v.taints.intern((taint.key, taint.value, taint.effect))
                if tid >= spec.max_taint_ids:
                    raise ValueError(
                        "distinct taint triples overflow "
                        "TableSpec.max_taint_ids"
                    )
                tk[i, j] = tid
                te[i, j] = taint.effect
            zid = (
                v.zones.intern(labels[ZONE_LABEL])
                if ZONE_LABEL in labels else NONE_ID
            )
            rid = (
                v.regions.intern(labels[REGION_LABEL])
                if REGION_LABEL in labels else NONE_ID
            )
            if zid >= spec.max_zones or rid >= spec.max_regions:
                raise ValueError(
                    "zone/region id overflow; grow "
                    "TableSpec.max_zones/max_regions"
                )
            zone[i] = zid
            region[i] = rid
            name_id[i] = v.node_names.intern(node.name)
            cpu[i] = node.cpu_milli
            mem[i] = node.mem_kib
            pods[i] = node.pods
        # Every node validated: allocate rows (capacity pre-checked;
        # journal + epoch side effects in batch order, exactly like the
        # loop) and land the blocks in one write per column.
        rows = self.bulk_alloc([node.name for node in nodes])
        self.valid[rows] = True
        self.cpu_alloc[rows] = cpu
        self.mem_alloc[rows] = mem
        self.pods_alloc[rows] = pods
        self.label_key[rows] = lk
        self.label_val[rows] = lv
        self.label_num[rows] = ln
        self.taint_id[rows] = tk
        self.taint_effect[rows] = te
        self.zone[rows] = zone
        self.region[rows] = region
        self.name_id[rows] = name_id
        _BULK_ROWS.inc(b)
        return rows

    def remove(self, name: str) -> int:
        row = self._row_of.pop(name)
        self.valid[row] = False
        # Zero the row so stale ids can't match future queries.
        for arr in (
            self.cpu_alloc, self.mem_alloc, self.pods_alloc,
            self.cpu_req, self.mem_req, self.pods_req,
            self.zone, self.region, self.name_id,
        ):
            arr[row] = 0
        for arr in (
            self.label_key, self.label_val, self.label_num,
            self.taint_id, self.taint_effect,
        ):
            arr[row] = 0
        if self.wave_epoch:
            # Two-phase free: the row is tombstoned now (the caller
            # scatters valid=0 immediately) but its id stays quarantined
            # until every wave launched at or before this epoch retires
            # (see release_rows) — the row-aliasing guard that lets a
            # pipelined coordinator apply removes without a quiesce.
            self._quarantine.append((self.wave_epoch, row))
        else:
            self._free_rows.append(row)
        self.epoch += 1
        if self._row_journal is not None:
            self._row_journal.append((name, row, False))
        return row

    # ---- wave epochs ----------------------------------------------------

    def begin_wave(self) -> int:
        """Stamp one device-wave launch; returns the wave's epoch."""
        self.wave_epoch += 1
        return self.wave_epoch

    def release_rows(self, before_epoch: int | None = None) -> int:
        """Return quarantined rows to the free list.

        ``before_epoch`` is the oldest still-in-flight wave's epoch: a
        row removed at epoch E is only referenced by waves launched at
        epoch <= E, so it is safe once ``E < before_epoch``.  ``None``
        (no wave in flight) releases everything.  Returns the count.
        """
        n = 0
        q = self._quarantine
        while q and (before_epoch is None or q[0][0] < before_epoch):
            self._free_rows.append(q.popleft()[1])
            n += 1
        return n

    @property
    def quarantined(self) -> int:
        return len(self._quarantine)

    def add_pod(self, name: str, cpu_milli: int, mem_kib: int) -> None:
        """Account an already-bound pod (host mirror of commit_binds)."""
        row = self._row_of[name]
        self.cpu_req[row] += cpu_milli
        self.mem_req[row] += mem_kib
        self.pods_req[row] += 1

    def remove_pod(self, name: str, cpu_milli: int, mem_kib: int) -> None:
        row = self._row_of[name]
        self.cpu_req[row] -= cpu_milli
        self.mem_req[row] -= mem_kib
        self.pods_req[row] -= 1

    @property
    def num_nodes(self) -> int:
        return len(self._row_of)

    # ---- device transfer ------------------------------------------------

    def to_device(self, sharding=None) -> NodeTable:
        def put(x):
            if x.dtype != np.bool_:
                # Narrow mirror columns (mirror_dtype rule) widen back
                # to the canonical device int32; no-copy when already
                # int32, so the wide columns transfer as before.
                x = np.asarray(x, np.int32)
            return jax.device_put(jnp.asarray(x), sharding) if sharding else jnp.asarray(x)

        return NodeTable(
            valid=put(self.valid),
            cpu_alloc=put(self.cpu_alloc),
            mem_alloc=put(self.mem_alloc),
            pods_alloc=put(self.pods_alloc),
            cpu_req=put(self.cpu_req),
            mem_req=put(self.mem_req),
            pods_req=put(self.pods_req),
            label_key=put(self.label_key),
            label_val=put(self.label_val),
            label_num=put(self.label_num),
            taint_id=put(self.taint_id),
            taint_effect=put(self.taint_effect),
            zone=put(self.zone),
            region=put(self.region),
            name_id=put(self.name_id),
        )


class RowVersions:
    """Monotone per-row mutation journal: the dirty bookkeeping that
    feeds the delta-plane cache's invalidation (engine/deltacache.py).

    Every batch of device-table row mutations — dirty-row scatters,
    retired bind commits, eviction repairs — is noted here with one
    version stamp; a consumer holding per-row derived state (a cached
    feasibility/score plane) records the version it was computed at and
    asks ``rows_since(v)`` for exactly the rows that moved afterwards.
    The journal is bounded: when it outgrows ``cap`` the oldest entries
    compact away and ``floor`` rises — a consumer whose recorded
    version sits below ``floor`` can no longer enumerate its delta and
    must treat its state as wholly stale (recompute, don't guess).
    That is the fail-closed direction: compaction can only ever force
    extra recompute, never hide a moved row.
    """

    def __init__(self, cap: int = 1 << 16) -> None:
        self.cap = cap
        self.ver = 0
        # Versions below this are compacted out of the journal: a
        # consumer stamped older than floor cannot enumerate its delta.
        self.floor = 0
        self._journal: collections.deque[tuple[int, int]] = (
            collections.deque()
        )

    def note(self, rows) -> int:
        """Stamp one mutation batch; returns the new version."""
        self.ver += 1
        v = self.ver
        self._journal.extend((v, int(r)) for r in rows)
        if len(self._journal) > self.cap:
            self.compact(keep=self.cap // 2)
        return v

    def compact(self, keep: int) -> None:
        """Drop the oldest entries down to ``keep``, raising ``floor``
        to the newest dropped version (consumers below it go stale)."""
        q = self._journal
        while len(q) > keep:
            v, _ = q.popleft()
            self.floor = max(self.floor, v)

    def release(self, before_ver: int) -> None:
        """Drop entries at versions < ``before_ver`` WITHOUT staling
        consumers at or past it (the caller proved every live consumer
        is stamped >= before_ver)."""
        q = self._journal
        while q and q[0][0] < before_ver:
            q.popleft()
        self.floor = max(self.floor, before_ver - 1)

    def rows_since(self, ver: int) -> set | None:
        """Rows mutated at versions > ``ver``; None when ``ver`` is
        below the compaction floor (the delta is unenumerable — treat
        everything as dirty)."""
        if ver < self.floor:
            return None
        out: set[int] = set()
        for v, r in reversed(self._journal):
            if v <= ver:
                break
            out.add(r)
        return out

    def __len__(self) -> int:
        return len(self._journal)


# ---- jit-side mutation ----------------------------------------------------


def commit_binds(
    table: NodeTable,
    node_idx: jax.Array,   # i32[B] row of the node each pod bound to (or any row if invalid)
    cpu_milli: jax.Array,  # i32[B]
    mem_kib: jax.Array,    # i32[B]
    bound: jax.Array,      # bool[B] — pod actually bound this cycle
) -> NodeTable:
    """Fold this batch's bind decisions into requested-resources.

    The reference achieves the same feedback through the scheduler cache's
    AssumePod immediately after Permit (the bind write to the apiserver is
    async); here the batch commit *is* the assume step.
    """
    cpu = jnp.where(bound, cpu_milli, 0)
    mem = jnp.where(bound, mem_kib, 0)
    one = jnp.where(bound, 1, 0).astype(jnp.int32)
    return table.replace(
        cpu_req=table.cpu_req.at[node_idx].add(cpu),
        mem_req=table.mem_req.at[node_idx].add(mem),
        pods_req=table.pods_req.at[node_idx].add(one),
    )


def apply_delta(table: NodeTable, rows: jax.Array, delta: NodeTable) -> NodeTable:
    """Scatter a batch of changed rows (host-compiled) into the device table.

    ``delta`` holds D rows of freshly-compiled node features; ``rows`` are
    their destinations.  This is the device half of the coordinator's
    revision-ordered informer stream.
    """
    return jax.tree.map(lambda t, d: t.at[rows].set(d), table, delta)


# Column split for the coordinator's dirty-row scatters: capacity/feature
# columns carry what the node object says (host always authoritative);
# the request columns carry bind accounting, which in a pipelined
# coordinator includes in-flight assumes the host mirror does not know
# yet.  A capacity-only node update therefore scatters CAP_COLUMNS alone,
# leaving the device's running request totals (the assume chain) intact.
CAP_COLUMNS = (
    "valid", "cpu_alloc", "mem_alloc", "pods_alloc",
    "label_key", "label_val", "label_num",
    "taint_id", "taint_effect", "zone", "region", "name_id",
)
REQ_COLUMNS = ("cpu_req", "mem_req", "pods_req")
ALL_COLUMNS = CAP_COLUMNS + REQ_COLUMNS


def scatter_rows(table: NodeTable, rows, delta: dict) -> NodeTable:
    """Scatter per-column host values into ``rows`` of the device table
    (the keys of ``delta`` select the columns — see CAP_COLUMNS)."""
    updates = {
        name: getattr(table, name).at[rows].set(arr)
        for name, arr in delta.items()
    }
    return table.replace(**updates)
