"""megarow bulk ingest: store bytes -> host mirror off the per-node axis.

The 1M-row cold build (ROADMAP item 1) hits two Python walls before the
device ever sees a byte: decoding a million stored Node objects
(control/objects.decode_node, ~16us each) and folding them into the
host mirror one ``upsert`` at a time (~20us each) — minutes of silent
stall at the paper's headline shape.  This module is the vectorized
lane the coordinator's bootstrap/resync relist feeds through instead:

- **Canonical grammar, not a parser.**  Values written by
  ``encode_node`` (the make_nodes registration lane, KWOK nodes) are
  FULLMATCHED against ``objects.CANONICAL_NODE_RE`` — one C-level
  regex whose captures (name, raw label blob, cpu/mem/pods) parse
  byte-identically to ``json.loads`` by construction.  Any other shape
  (taints, unschedulable, heartbeat-churned status, escapes) drops the
  chunk to the exact ``decode_node`` + ``NodeTableHost.bulk_upsert``
  path.

- **Label blobs are templates.**  A fleet's label sets repeat — the
  blob bytes between ``"labels":{`` and ``}`` take a few hundred
  distinct values across a million KWOK nodes (zones x regions x
  groups), because the one per-node label (the hostname default) is
  *added by the table*, not stored.  Each distinct blob is parsed,
  sorted and interned once into a row template (the node-side analogue
  of hotfeed's per-shape pod encode templates); per node only the
  hostname value and node name intern, and the column blocks fill by
  one vectorized gather per template stack instead of per-node writes.

**Byte-identity is the contract** (tier-1 differential,
tests/test_megarow.py): ``BulkNodeLoader.ingest`` produces the same
column bytes, row mapping, vocab contents *in the same intern order*,
epoch count and row-journal entries as the equivalent
``host.upsert(decode_node(v))`` loop.  Intern-order equality is why
the scan is strictly sequential: a template's strings intern at its
first node exactly as ``upsert`` would (sorted label order, hostname
value in place), and later nodes intern only their hostname value and
name at their own position in the stream.
"""

from __future__ import annotations

import numpy as np

from k8s1m_tpu.config import NONE_ID
from k8s1m_tpu.control.objects import (
    CANONICAL_LABEL_RE,
    CANONICAL_NODE_RE,
    decode_node,
)
from k8s1m_tpu.snapshot.interning import numeric_of
from k8s1m_tpu.snapshot.node_table import (
    _BULK_ROWS,
    HOSTNAME_LABEL,
    REGION_LABEL,
    ZONE_LABEL,
    NodeTableHost,
)

# A chunk is the ingest transaction unit: one non-canonical value drops
# its whole chunk to the exact NodeInfo path (all-or-nothing keeps the
# intern-order proof simple), and the transient per-chunk Python lists
# stay bounded at 1M+ rows.
DEFAULT_CHUNK = 65536


class _Template:
    """One distinct label blob, pre-compiled to column rows."""

    __slots__ = ("lk", "lv", "ln", "hpos", "zid", "rid")

    def __init__(self, lk, lv, ln, hpos, zid, rid):
        self.lk, self.lv, self.ln = lk, lv, ln
        self.hpos = hpos
        self.zid, self.rid = zid, rid


class BulkNodeLoader:
    """Stateful bulk lane over one ``NodeTableHost`` (templates and the
    bytes->str memo persist across ``ingest`` calls, so a resync pays
    the blob parse only for blobs it has never seen)."""

    def __init__(
        self,
        host: NodeTableHost,
        *,
        template_cap: int = 4096,
        chunk: int = DEFAULT_CHUNK,
    ) -> None:
        self.host = host
        self.template_cap = template_cap
        self.chunk = chunk
        # blob bytes -> _Template, or None for "parse per node" blobs
        # (explicit hostname label, label overflow): those vary per
        # node or must raise with upsert's exact message.
        self._templates: dict[bytes, _Template | None] = {}
        # Stacked template columns, rebuilt lazily when templates grow.
        self._stack: tuple | None = None
        self._tlist: list[_Template] = []
        # bytes -> decoded str memo for label keys/values (shared str
        # objects also hash once across every later intern lookup).
        self._str: dict[bytes, str] = {}

    # -- template compilation -------------------------------------------

    def _decode_str(self, b: bytes) -> str:
        s = self._str.get(b)
        if s is None:
            s = b.decode()
            if len(self._str) < 1 << 16:
                self._str[b] = s
        return s

    def _compile(self, blob: bytes, first_name: str) -> _Template | None:
        """Intern and row-compile one new blob, in exactly the order
        ``upsert`` would for its first node (``first_name`` supplies
        the hostname default interned mid-pass)."""
        host = self.host
        spec = host.spec
        labels: dict[str, str] = {}
        for kb, vb in CANONICAL_LABEL_RE.findall(blob):
            labels[self._decode_str(kb)] = self._decode_str(vb)
        if HOSTNAME_LABEL in labels or len(labels) + 1 > spec.label_slots:
            # Hostname-carrying blobs differ per node (no reuse, and an
            # unbounded cache); oversized ones must raise with upsert's
            # own message.  Both take the exact path, every time.
            self._templates[blob] = None
            return None
        v = host.vocab
        slots = spec.label_slots
        lk = np.zeros((slots,), np.int32)
        lv = np.zeros((slots,), np.int32)
        ln = np.zeros((slots,), np.int32)
        hpos = 0
        full = sorted(labels.items())
        full.append((HOSTNAME_LABEL, None))
        full.sort(key=lambda kv: kv[0])
        for j, (k, val) in enumerate(full):
            lk[j] = v.label_keys.intern(k)
            if val is None:
                hpos = j
                # The first node's hostname value interns here — in the
                # upsert order — and is overwritten per node below.
                lv[j] = v.label_values.intern(first_name)
            else:
                lv[j] = v.label_values.intern(val)
                ln[j] = numeric_of(val)
        zid = (
            v.zones.intern(labels[ZONE_LABEL])
            if ZONE_LABEL in labels else NONE_ID
        )
        rid = (
            v.regions.intern(labels[REGION_LABEL])
            if REGION_LABEL in labels else NONE_ID
        )
        if zid >= spec.max_zones or rid >= spec.max_regions:
            raise ValueError(
                "zone/region id overflow; grow "
                "TableSpec.max_zones/max_regions"
            )
        t = _Template(lk, lv, ln, hpos, zid, rid)
        self._templates[blob] = t
        self._tlist.append(t)
        self._stack = None
        return t

    def _stacked(self) -> tuple:
        if self._stack is None:
            tl = self._tlist
            self._stack = (
                np.stack([t.lk for t in tl]),
                np.stack([t.lv for t in tl]),
                np.stack([t.ln for t in tl]),
                np.asarray([t.hpos for t in tl], np.int64),
                np.asarray([t.zid for t in tl], np.int32),
                np.asarray([t.rid for t in tl], np.int32),
            )
        return self._stack

    # -- ingest ----------------------------------------------------------

    def ingest(self, values) -> np.ndarray:
        """Upsert every encoded Node value (store bytes) into the host
        mirror; returns their rows in input order.  Byte-identical to
        ``[host.upsert(decode_node(v)) for v in values]``."""
        out = []
        for off in range(0, len(values), self.chunk):
            out.append(self._ingest_chunk(values[off:off + self.chunk]))
        if not out:
            return np.empty((0,), np.int64)
        return out[0] if len(out) == 1 else np.concatenate(out)

    def _ingest_chunk(self, values) -> np.ndarray:
        host = self.host
        v = host.vocab
        lv_id, lv_val = v.label_values._to_id, v.label_values._to_val
        nn_id, nn_val = v.node_names._to_id, v.node_names._to_val
        templates = self._templates
        fullmatch = CANONICAL_NODE_RE.fullmatch
        names: list[str] = []
        tmpl_idx: list[int] = []
        cpu: list[int] = []
        mem: list[int] = []
        pods: list[int] = []
        hid: list[int] = []
        nid: list[int] = []
        hnum: list[int] = []
        index_of = {id(t): i for i, t in enumerate(self._tlist)}
        for val in values:
            m = fullmatch(val)
            t = None
            if m is not None:
                blob = m.group(2)
                t = templates.get(blob)
                if t is None and blob not in templates:
                    if len(templates) >= self.template_cap:
                        t = None
                    else:
                        t = self._compile(blob, m.group(1).decode())
                        if t is not None:
                            index_of[id(t)] = len(self._tlist) - 1
            if t is None:
                # Non-canonical value / per-node blob / cache full: the
                # whole chunk takes the exact decode + bulk_upsert path
                # (prefix interning above matches the loop's order, so
                # re-interning below hits the same ids).
                return host.bulk_upsert([decode_node(x) for x in values])
            name = m.group(1).decode()
            names.append(name)
            tmpl_idx.append(index_of[id(t)])
            cpu.append(int(m.group(3)))
            mem.append(int(m.group(4)))
            pods.append(int(m.group(5)))
            # Hostname value and node name intern NOW, at this node's
            # position in the stream (intern-order identity).
            i = lv_id.get(name)
            if i is None:
                i = len(lv_val)
                lv_id[name] = i
                lv_val.append(name)
            hid.append(i)
            hnum.append(numeric_of(name))
            i = nn_id.get(name)
            if i is None:
                i = len(nn_val)
                nn_id[name] = i
                nn_val.append(name)
            nid.append(i)
        b = len(names)
        if not b:
            return np.empty((0,), np.int64)
        tlk, tlv, tln, thpos, tzid, trid = self._stacked()
        tidx = np.asarray(tmpl_idx, np.int64)
        ar = np.arange(b)
        lk_b = tlk[tidx]
        lv_b = tlv[tidx]
        ln_b = tln[tidx]
        hpos_b = thpos[tidx]
        lv_b[ar, hpos_b] = np.asarray(hid, np.int32)
        ln_b[ar, hpos_b] = np.asarray(hnum, np.int32)
        rows = host.bulk_alloc(names)
        host.valid[rows] = True
        host.cpu_alloc[rows] = np.asarray(cpu, np.int32)
        host.mem_alloc[rows] = np.asarray(mem, np.int32)
        host.pods_alloc[rows] = np.asarray(pods, np.int32)
        host.label_key[rows] = lk_b
        host.label_val[rows] = lv_b
        host.label_num[rows] = ln_b
        # Canonical nodes carry no taints; a re-upserted row must still
        # clear whatever a prior tainted generation wrote.
        host.taint_id[rows] = 0
        host.taint_effect[rows] = 0
        host.zone[rows] = tzid[tidx].astype(host.zone.dtype)
        host.region[rows] = trid[tidx].astype(host.region.dtype)
        host.name_id[rows] = np.asarray(nid, np.int32)
        _BULK_ROWS.inc(b)
        return rows


def bulk_ingest(host: NodeTableHost, values) -> np.ndarray:
    """One-shot convenience over ``BulkNodeLoader`` (tools, tests)."""
    return BulkNodeLoader(host).ingest(values)
