from k8s1m_tpu.engine.cycle import Assignment, Candidates, schedule_batch, filter_score_topk

__all__ = ["Assignment", "Candidates", "schedule_batch", "filter_score_topk"]
