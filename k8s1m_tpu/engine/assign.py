"""Greedy in-batch conflict resolution over per-pod bind candidates.

The reference schedules pods concurrently and lets two pods race for one
node; the loser's bind fails at the apiserver and rolls back (reference
README.adoc:558-560, "optimistic concurrency").  Batched on TPU, the same
problem is solved *before* binding: every pod brings its top-K candidate
nodes (already sorted by packed priority), and a sequential lax.scan over
the batch commits pods in order, re-checking candidate capacity against
what earlier pods in the batch just took.  A pod whose K candidates are all
exhausted leaves the batch unbound and is retried next cycle — exactly the
reference's conflict-rollback, but at O(B*K) cost with no apiserver
round-trip.

The scan is tiny (B x K integers) and runs replicated on every device in
the sharded cycle, so no cross-device coordination is needed at commit time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from k8s1m_tpu.ops.priority import unpack_score


def greedy_assign(
    cand_idx,   # i32[B, K] global node rows, priority-descending (-1 = none)
    cand_prio,  # i32[B, K] packed priorities (-1 = infeasible)
    cand_cpu,   # i32[B, K] candidate's free cpu at batch start
    cand_mem,   # i32[B, K]
    cand_pods,  # i32[B, K] candidate's free pod slots at batch start
    pod_cpu,    # i32[B]
    pod_mem,    # i32[B]
    pod_valid,  # bool[B]
):
    """Returns (node_row i32[B] (-1 unbound), bound bool[B], score i32[B],
    chosen_k i32[B] — index of the winning candidate slot)."""
    b, k = cand_idx.shape
    arange_b = jnp.arange(b)

    def step(carry, _):
        node_of, bound, i = carry
        # Resources already taken from pod i's candidates by pods j < i.
        prev = (arange_b < i) & bound                       # [B]
        eq = cand_idx[i][:, None] == node_of[None, :]       # [K, B]
        taken = eq & prev[None, :]
        dcpu = (taken * pod_cpu[None, :]).sum(axis=-1)
        dmem = (taken * pod_mem[None, :]).sum(axis=-1)
        dpods = taken.sum(axis=-1)

        ok = (
            (cand_prio[i] >= 0)
            & (cand_idx[i] >= 0)
            & (pod_cpu[i] <= cand_cpu[i] - dcpu)
            & (pod_mem[i] <= cand_mem[i] - dmem)
            & (cand_pods[i] - dpods >= 1)
        )
        any_ok = ok.any() & pod_valid[i]
        # Candidates are priority-sorted, so the first feasible one is the
        # winner (argmax of bool returns the first True).
        kstar = jnp.argmax(ok)
        node = jnp.where(any_ok, cand_idx[i, kstar], -1)
        score = jnp.where(any_ok, unpack_score(cand_prio[i, kstar]), -1)
        carry = (node_of.at[i].set(node), bound.at[i].set(any_ok), i + 1)
        return carry, (node, any_ok, score, kstar.astype(jnp.int32))

    # xs=None + carried index: see engine/cycle.py on lifted-constant scans.
    init = (jnp.full((b,), -1, jnp.int32), jnp.zeros((b,), bool), jnp.int32(0))
    _, (node_row, bound, score, chosen_k) = lax.scan(step, init, None, length=b)
    return node_row, bound, score, chosen_k
