"""The scheduling cycle: filter -> score -> top-k -> assign -> commit.

One call schedules a whole batch of pods against the whole node table.
This is the TPU replacement for the reference's entire scatter/gather
pipeline: relay-tree broadcast, 256 shards running filter+score, the
CollectScore gather, DistPermit, and the bind-conflict rollback
(reference SURVEY.md §3.2).  ~560us of fleet CPU per pod becomes a few
microseconds of TPU time amortized over the batch.

The node axis is processed in fixed-size chunks with a lax.scan carrying a
running top-k: HBM traffic stays streaming (the table is read once per
batch), compute per chunk stays in VMEM-sized tiles, and peak memory is
O(B * chunk) instead of O(B * N).  Candidates carry their free-capacity
and topology-domain payload so the greedy conflict scan (engine/assign.py),
the constraint commit, and the sharded all-gather (parallel/) never have
to re-gather from the (possibly sharded) table.

In-batch semantics note: the greedy conflict scan re-checks *capacity*
for pods later in the batch, but not topology constraints — two same-batch
pods can land in a way that exceeds maxSkew by the batch size in the worst
case.  The reference has exactly the same window (256 shards bind
optimistically and only capacity conflicts roll back, reference
README.adoc:558-560); constraint counts are exact again at the next batch
boundary.  The pipelined coordinator widens the same window across waves:
capacity-only node deltas (allocatable, labels, taints, zone — same row,
same name) scatter into the live table while earlier waves are still in
flight, so a wave may score against capacity a heartbeat just changed.
That is the identical optimism — every bind is still CAS-verified against
the store, capacity conflicts still roll back through the dirty-row path,
and a wave that retires onto a row tombstoned mid-flight retries the pod —
so correctness is unchanged; only the staleness window is (bounded by
pipeline depth) wider.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import lax

from k8s1m_tpu.engine.assign import greedy_assign
from k8s1m_tpu.ops.priority import pack_hashed, seed_of
from k8s1m_tpu.plugins.registry import Profile, score_and_filter
from k8s1m_tpu.snapshot.constraints import (
    ConstraintState,
    commit_constraint_binds,
    slice_constraints,
)
from k8s1m_tpu.snapshot.node_table import NodeTable, commit_binds
from k8s1m_tpu.snapshot.packing import is_packed, mask_rows_packed, unpack_chunk
from k8s1m_tpu.snapshot.pod_encoding import PodBatch


@dataclasses.dataclass
class Wave:
    """One in-flight pipelined dispatch: everything the coordinator needs
    to retire the wave later (CAS the binds back, roll back conflicts).

    ``epoch`` is the snapshot wave-epoch stamped at launch
    (NodeTableHost.begin_wave): a node row removed at epoch E stays
    quarantined until every wave with ``epoch <= E`` has retired, which
    is what makes structural removes safe to apply while this wave is
    still in flight — no row the wave may still bind can be reused.
    """

    batch_pods: list
    batch: object       # PackedPodBatch as dispatched
    asg: "Assignment"   # device-resident; fetched only on rollback
    rows_dev: jax.Array  # i32[B] bound row per pod (-1 = unbound)
    t_start: float
    epoch: int
    # Podtrace span attributes stamped at launch (obs/podtrace.py):
    # in-flight depth including this wave, and which kernel pass ran
    # ("full" vs the deltacache "delta" path).
    depth: int = 1
    path: str = "full"
    # Candidate-index outcome (deltasched index waves only): the device
    # i32 flag the delta step returns (1 = candidates derived from the
    # index, 0 = the index failed closed to the plane tail), fetched at
    # retire alongside rows_dev; ``index_attempted`` is the host-side
    # trace decision (False = the dirty slice exceeded the in-step
    # cap); ``index_touched`` is the (index-path, plane-path) touched-
    # row pair for deltasched_index_touched_rows_total.
    index_flag_dev: object | None = None
    index_attempted: bool = False
    index_touched: tuple = (0, 0)


@struct.dataclass
class Candidates:
    """Top-K bind candidates per pod, with payload gathered at score time."""

    idx: jax.Array    # i32[B, K] global node rows (-1 = none)
    prio: jax.Array   # i32[B, K] packed priorities, descending (-1 = infeasible)
    cpu: jax.Array    # i32[B, K] candidate free cpu at batch start
    mem: jax.Array    # i32[B, K]
    pods: jax.Array   # i32[B, K]
    zone: jax.Array   # i32[B, K] candidate's topology domains
    region: jax.Array  # i32[B, K]


@struct.dataclass
class Assignment:
    node_row: jax.Array  # i32[B] (-1 = unbound, retry next batch)
    bound: jax.Array     # bool[B]
    score: jax.Array     # i32[B] integer plugin score of the chosen node
    zone: jax.Array      # i32[B] domain of the chosen node
    region: jax.Array    # i32[B]


@struct.dataclass
class CommitFields:
    """The slice of a PodBatch the post-candidate epilogue needs.

    In the sharded cycle only these leaves cross the dp all-gather — the
    selector tensors (req_vals, tolerated, ...) never leave their home
    device, keeping the hop at O(B*K) candidate records as the module doc
    promises."""

    cpu: jax.Array           # i32[B]
    mem: jax.Array           # i32[B]
    valid: jax.Array         # bool[B]
    sinc_valid: jax.Array    # spread-constraint commit increments
    sinc_cid: jax.Array
    sinc_topo: jax.Array
    iinc_valid: jax.Array    # affinity-term commit increments
    iinc_tid: jax.Array
    iinc_topo: jax.Array
    ipa_own_valid: jax.Array  # pod's own required anti-affinity terms
    ipa_tid: jax.Array
    ipa_topo: jax.Array


def commit_fields_np(fields: dict) -> CommitFields:
    """CommitFields from a PackedPodBatch's host field dict (np arrays are
    valid jit inputs; used on the rare CAS-rollback path)."""
    return CommitFields(
        cpu=fields["cpu"],
        mem=fields["mem"],
        valid=fields["valid"],
        sinc_valid=fields["sinc_valid"],
        sinc_cid=fields["sinc_cid"],
        sinc_topo=fields["sinc_topo"],
        iinc_valid=fields["iinc_valid"],
        iinc_tid=fields["iinc_tid"],
        iinc_topo=fields["iinc_topo"],
        ipa_own_valid=fields["ipa_valid"]
        & fields["ipa_required"]
        & fields["ipa_anti"],
        ipa_tid=fields["ipa_tid"],
        ipa_topo=fields["ipa_topo"],
    )


def commit_fields_of(batch: PodBatch) -> CommitFields:
    return CommitFields(
        cpu=batch.cpu,
        mem=batch.mem,
        valid=batch.valid,
        sinc_valid=batch.sinc_valid,
        sinc_cid=batch.sinc_cid,
        sinc_topo=batch.sinc_topo,
        iinc_valid=batch.iinc_valid,
        iinc_tid=batch.iinc_tid,
        iinc_topo=batch.iinc_topo,
        ipa_own_valid=batch.ipa_valid & batch.ipa_required & batch.ipa_anti,
        ipa_tid=batch.ipa_tid,
        ipa_topo=batch.ipa_topo,
    )


def _slice_table(table: NodeTable, start, chunk: int) -> NodeTable:
    """Chunk slice of the node table; a PACKED table decodes here, inside
    the jitted scan body, so HBM holds only the packed planes and the
    i32-wide decode lives in the same fused pass as the plugins
    (snapshot/packing.py — the devicestate layout contract)."""
    sliced = jax.tree.map(
        lambda a: lax.dynamic_slice_in_dim(a, start, chunk, axis=0), table
    )
    return unpack_chunk(sliced) if is_packed(sliced) else sliced


def _prologue_stats(table, constraints, axis_name: str | None = None):
    """topology.prologue over either layout: the prologue needs only the
    full valid/zone/region columns, which a packed table decodes ONCE per
    wave (global domain statistics don't belong in a chunk decode).
    ``axis_name`` is the shard_map node-shard axis ("sp") so the sharded
    cycle shares this decode: domain reductions cross shards while the
    DomainView decode stays shard-local."""
    from k8s1m_tpu.plugins import topology

    view = table.domain_view() if is_packed(table) else table
    return topology.prologue(view, constraints, axis_name=axis_name)


def topk_by_argmax(prio, k: int):
    """``lax.top_k`` semantics (descending values, earlier index wins
    ties) as k argmax knock-out passes — the CPU-backend form.

    The chunk scan only ever needs tiny k (4) over wide rows (the node
    chunk): a full TopK sort is the wrong primitive — XLA CPU's TopK
    custom-call runs ~200ns/element on [4096, 16384] int32 (13.4s per
    wave!) where an argmax pass is ~2ns/element; the fused pallas kernel
    already extracts its running top-k by repeated max for the same
    reason (ops/pallas_topk.py).  k linear passes beat one sort on both
    backends whenever k is small.

    On TPU this form is the wrong one: XLA-TPU hung >30min compiling the
    1M-node scan built on it (round-5 chip batch; the same program
    compiles in 14.5s and runs fine on XLA CPU), while `lax.top_k` — a
    native TPU primitive — compiled the identical scan in ~40s pre-round-4.
    `chunk_topk` below picks per backend; both forms implement exactly
    top_k's tie rule (descending, earlier index wins), so backend parity
    (pallas vs xla bit-identical, tests/test_pallas_topk.py) is
    unaffected by the switch.

    A grouped tournament variant (one max pass + per-extraction rescans
    of only the winning 128-wide group) measured 8x faster standalone
    but 12x SLOWER inside the jitted wave — XLA CPU handles the
    per-extraction dynamic gathers pathologically in context, with or
    without an optimization_barrier on the fused producer.  Keep the
    knock-out form: it fuses cleanly with the filter+score producer.
    """
    iota = lax.broadcasted_iota(jnp.int32, prio.shape, prio.ndim - 1)
    lowest = (
        jnp.iinfo(prio.dtype).min
        if jnp.issubdtype(prio.dtype, jnp.integer) else -jnp.inf
    )
    vals, idxs = [], []
    p = prio
    for _ in range(k):
        i = jnp.argmax(p, axis=-1).astype(jnp.int32)
        # Values come from the ORIGINAL array (the knock-out sentinel
        # must never surface), and duplicates extract in increasing
        # index order — both exactly top_k's tie rule.
        vals.append(jnp.take_along_axis(prio, i[..., None], axis=-1))
        idxs.append(i[..., None])
        p = jnp.where(iota == i[..., None], lowest, p)
    return (
        jnp.concatenate(vals, axis=-1),
        jnp.concatenate(idxs, axis=-1),
    )


def chunk_topk(prio, k: int):
    """Per-backend top-k over the chunk axis (see topk_by_argmax doc).

    CPU: k argmax knock-out passes (TopK custom-call is ~100x slower).
    TPU/other: native ``lax.top_k`` (the knock-out form hangs XLA-TPU's
    compiler at 1M-node scan sizes).  Identical semantics either way
    PROVIDED the input never contains int32 min — the knock-out's own
    sentinel; ``pack_hashed`` emits {-1} ∪ [0, int32max], so the packed
    -priority domain satisfies this (asserted by
    test_topk_by_argmax_matches_lax_top_k).  The backend choice is
    trace-time static, so this costs nothing inside jit.

    Coverage caveat (round-5 advisory): the two forms' equivalence —
    including the earlier-index-wins tie-break — is asserted by the CPU
    tier-1 suite only, where BOTH forms run on the CPU backend.  The
    TPU branch's tie semantics (``lax.top_k`` on silicon) are covered
    exclusively by the on-chip parity suite (tests/test_pallas_topk.py
    via the recovery-daemon batch), not by any CPU run.
    """
    if jax.default_backend() == "cpu":
        return topk_by_argmax(prio, k)
    top, idx = lax.top_k(prio, k)
    return top, idx.astype(jnp.int32)


def merge_topk(a: Candidates, b: Candidates, k: int) -> Candidates:
    """Merge two candidate sets, keeping the k highest priorities."""
    prio = jnp.concatenate([a.prio, b.prio], axis=-1)
    top_prio, sel = lax.top_k(prio, k)

    def take(xa, xb):
        return jnp.take_along_axis(jnp.concatenate([xa, xb], axis=-1), sel, axis=-1)

    return jax.tree.map(take, a, b).replace(prio=top_prio)


def empty_candidates(b: int, k: int) -> Candidates:
    zeros = jnp.zeros((b, k), jnp.int32)
    return Candidates(
        idx=jnp.full((b, k), -1, jnp.int32),
        prio=jnp.full((b, k), -1, jnp.int32),
        cpu=zeros, mem=zeros, pods=zeros, zone=zeros, region=zeros,
    )


def filter_score_topk(
    table: NodeTable,
    batch: PodBatch,
    key: jax.Array,
    profile: Profile,
    *,
    chunk: int,
    k: int,
    constraints: ConstraintState | None = None,
    stats=None,
    row_offset=0,
    pod_offset=0,
    stratum_bits: int = 0,
) -> Candidates:
    """Stream the node table in chunks, keeping each pod's top-k candidates.

    ``row_offset`` biases emitted node rows — under shard_map each shard
    passes its global row offset so candidate indices stay global.  It
    also biases the tie-break hash's node coordinate, so a shard hashing
    its local rows draws the SAME jitter a single device drew for those
    global rows.  ``pod_offset`` does the same for the pod coordinate (a
    dp shard passes its batch-block offset).  Together they make the
    sharded cycle's priorities a pure function of (seed, global pod row,
    global node row) — the byte-identity contract the mesh differential
    gate rests on (tests/test_mesh_differential.py).
    """
    n = table.num_rows
    if n % chunk:
        raise ValueError(f"table rows {n} not divisible by chunk {chunk}")
    num_chunks = n // chunk
    b = batch.batch
    if constraints is not None and stats is None:
        # Single-device convenience: build the batch prologue here.  Under
        # shard_map callers MUST pass stats from topology.prologue(...,
        # axis_name=...) — the auto-built one would be shard-local.
        stats = _prologue_stats(table, constraints)

    # ONE scalar threefry draw per wave; per-element jitter comes from the
    # separable hash over (pod row, view-local node column) — the same
    # stream the pallas kernel computes, so the two backends produce
    # identical priorities for the same wave (and the counter-mode PRNG,
    # ~1.8s per [4096,16384] wave on XLA CPU, leaves the hot loop).
    seed = seed_of(key)
    pod_rows = lax.broadcasted_iota(jnp.int32, (b, 1), 0) + pod_offset

    def body(carry, _):
        carry, ci = carry
        start = ci * chunk
        tchunk = _slice_table(table, start, chunk)
        cchunk = (
            slice_constraints(constraints, start, chunk)
            if constraints is not None else None
        )
        mask, score = score_and_filter(tchunk, batch, profile, cchunk, stats)
        node_cols = (
            lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
            + start + row_offset
        )
        prio = pack_hashed(score, seed, mask, pod_rows, node_cols, stratum_bits)
        top_prio, idx = chunk_topk(prio, k)                     # [B, k]
        free_cpu, free_mem, free_pods = tchunk.free()
        local = Candidates(
            idx=(idx + start + row_offset).astype(jnp.int32),
            prio=top_prio,
            cpu=jnp.take(free_cpu, idx),
            mem=jnp.take(free_mem, idx),
            pods=jnp.take(free_pods, idx),
            zone=jnp.take(tchunk.zone, idx),
            region=jnp.take(tchunk.region, idx),
        )
        return (merge_topk(carry, local, k), ci + 1), None

    # NB: scan without an xs array — a `jnp.arange(num_chunks)` here gets
    # lifted to an executable constant, which the pjit fast-path cache
    # mishandles when one function owns multiple executables ("supplied 66
    # buffers but compiled program expected 67").
    init = (empty_candidates(b, k), jnp.int32(0))
    if num_chunks == 1:
        (cand, _), _ = body(init, None)
    else:
        (cand, _), _ = lax.scan(body, init, None, length=num_chunks)
    # Mark infeasible candidates' rows as -1 so downstream never binds them.
    return cand.replace(idx=jnp.where(cand.prio >= 0, cand.idx, -1))


def commit_constraints_for_batch(
    constraints: ConstraintState,
    fields: CommitFields,
    asg: "Assignment",
    node_row,       # i32[B] rows to scatter node-domain counts into
    bound_node,     # bool[B] gate for node-domain tables (shard-local mask)
    bound_domain,   # bool[B] gate for zone/region tables (global mask)
) -> ConstraintState:
    return commit_constraint_binds(
        constraints,
        bound_node, bound_domain, node_row, asg.zone, asg.region,
        fields.sinc_valid, fields.sinc_cid, fields.sinc_topo,
        fields.iinc_valid, fields.iinc_tid, fields.iinc_topo,
        fields.ipa_own_valid, fields.ipa_tid, fields.ipa_topo,
    )


def finalize_batch(
    table: NodeTable,
    constraints: ConstraintState | None,
    cand: Candidates,
    fields: CommitFields,
    *,
    row_offset: int | jax.Array = 0,
    rows: int | None = None,
):
    """Shared epilogue: greedy conflict resolution + capacity/constraint
    commit.  ``rows=None`` means the whole table is local (single device);
    otherwise only binds landing in [row_offset, row_offset+rows) update
    this shard's node-row tables, while zone/region count tables (replicated
    in the sharded cycle) take the full global update.

    Returns (table, constraints, Assignment)."""
    node_row, bound, score, chosen_k = greedy_assign(
        cand.idx, cand.prio, cand.cpu, cand.mem, cand.pods,
        fields.cpu, fields.mem, fields.valid,
    )
    take1 = lambda x: jnp.take_along_axis(x, chosen_k[:, None], axis=1)[:, 0]
    asg = Assignment(
        node_row=node_row, bound=bound, score=score,
        zone=jnp.where(bound, take1(cand.zone), 0),
        region=jnp.where(bound, take1(cand.region), 0),
    )
    if rows is None:
        local = bound
        local_row = jnp.where(bound, node_row, 0)
    else:
        local = bound & (node_row >= row_offset) & (node_row < row_offset + rows)
        local_row = jnp.where(local, node_row - row_offset, 0)
    table = commit_binds(table, local_row, fields.cpu, fields.mem, local)
    if constraints is not None:
        constraints = commit_constraints_for_batch(
            constraints, fields, asg, local_row, local, bound
        )
    return table, constraints, asg


def adjust_constraints_impl(
    constraints: ConstraintState,
    fields: CommitFields,
    node_row,      # i32[B] (clipped to a valid row where mask_node is off)
    zone,          # i32[B]
    region,        # i32[B]
    mask_node,     # bool[B] gate for node-domain tables
    mask_domain,   # bool[B] gate for zone/region tables
    sign: int = -1,
) -> ConstraintState:
    """Signed constraint-count correction outside the scheduling step.

    Used by the coordinator for bind-CAS conflicts (sign=-1: the step's
    optimistic commit must be rolled back for pods whose store write lost)
    and for pod deletions (sign=-1 against the recorded bind placement;
    mask_node is off when the node has since been removed, while the
    zone/region decrement still applies via mask_domain).
    """
    return commit_constraint_binds(
        constraints,
        mask_node, mask_domain, jnp.where(mask_node, node_row, 0), zone, region,
        fields.sinc_valid, fields.sinc_cid, fields.sinc_topo,
        fields.iinc_valid, fields.iinc_tid, fields.iinc_topo,
        fields.ipa_own_valid, fields.ipa_tid, fields.ipa_topo,
        sign=sign,
    )


# Correction path, not the per-wave hot loop: callers (tests, the
# coordinator's rollback batches) may replay against the same state.
adjust_constraints = jax.jit(  # graftlint: disable=undonated-device-update (replayable correction path; per-wave commits donate via _jitted_schedule_packed)
    adjust_constraints_impl, static_argnames=("sign",)
)


def _schedule_batch_impl(
    table: NodeTable,
    batch: PodBatch,
    key: jax.Array,
    constraints: ConstraintState | None,
    profile: Profile,
    chunk: int,
    k: int,
    backend: str = "xla",
    with_affinity: bool = True,
    src: NodeTable | None = None,
    stratum_bits: int = 0,
):
    # ``src`` (default: the table itself) is the candidate-selection view;
    # binds always commit into ``table`` — the split that makes ownership
    # masks (mask_rows) work without touching commit state.
    src = table if src is None else src
    stats = None
    if constraints is not None:
        # Domain statistics are GLOBAL by semantics (a spread
        # constraint's min/max is over the whole cluster): build them
        # from the commit table, not the candidate view — an ownership
        # mask (mask_rows) must narrow candidate selection, never the
        # skew baseline, or shards would disagree on feasibility.  The
        # sampling path below applies the same rule.
        stats = _prologue_stats(table, constraints)
    if backend == "pallas":
        from k8s1m_tpu.ops.pallas_topk import pallas_candidates

        cand = pallas_candidates(
            src, batch, key, profile, chunk=chunk, k=k,
            with_affinity=with_affinity,
            constraints=constraints, stats=stats,
            stratum_bits=stratum_bits,
        )
    else:
        cand = filter_score_topk(
            src, batch, key, profile,
            chunk=chunk, k=k, constraints=constraints, stats=stats,
            stratum_bits=stratum_bits,
        )
    return finalize_batch(table, constraints, cand, commit_fields_of(batch))


@functools.lru_cache(maxsize=64)
def _jitted_schedule(
    profile: Profile, chunk: int, k: int, with_constraints: bool,
    backend: str = "xla", with_affinity: bool = True,
    stratum_bits: int = 0,
):
    # One jax.jit function object per static configuration.  Routing every
    # configuration through a single jitted function trips a pjit fast-path
    # cache bug in this environment once the function owns several
    # executables ("Execution supplied 66 buffers but compiled program
    # expected 67 buffers"); distinct function identities sidestep it.
    if with_constraints:
        fn = lambda table, batch, key, constraints: _schedule_batch_impl(
            table, batch, key, constraints, profile, chunk, k, backend,
            with_affinity=with_affinity, stratum_bits=stratum_bits,
        )
    else:
        fn = lambda table, batch, key: _schedule_batch_impl(
            table, batch, key, None, profile, chunk, k, backend,
            with_affinity=with_affinity, stratum_bits=stratum_bits,
        )
    # schedule_batch is the unpacked replay/test surface (differential
    # suites re-run one table); the production path is schedule_batch_
    # packed with donate=True.
    return jax.jit(fn)  # graftlint: disable=undonated-device-update (replay surface; production donates via _jitted_schedule_packed)


def schedule_batch(
    table: NodeTable,
    batch: PodBatch,
    key: jax.Array,
    *,
    profile: Profile,
    constraints: ConstraintState | None = None,
    chunk: int = 16384,
    k: int = 4,
    backend: str = "xla",
    with_affinity: bool = True,
    stratum_bits: int = 0,
):
    """Schedule one pod batch end-to-end on a single device.

    Returns (new_table, new_constraints, Assignment).  The table and
    constraint counts come back with this batch's binds already folded in
    (the assume step), so back-to-back batches see each other's placements.

    ``backend="pallas"`` routes filter+score+top-k through the fused
    Pallas kernel (ops/pallas_topk.py), including the constraint stage
    when ``constraints`` is passed (BASELINE configs 3-4 fused).
    ``with_affinity=False`` compiles the cheaper selector-free kernel;
    pass it only when the caller knows no pod in the batch carries
    nodeSelector/affinity terms (the packed path derives this per wave
    from the field groups).
    """
    if backend == "pallas" and constraints is None:
        from k8s1m_tpu.ops import pallas_topk

        if not pallas_topk.supports(profile):
            raise ValueError(
                "profile enables constraint plugins but no constraint "
                "state was passed (see ops/pallas_topk.py)"
            )
    step = _jitted_schedule(
        profile, chunk, k, constraints is not None, backend, with_affinity,
        stratum_bits,
    )
    if constraints is None:
        table, cons, asg = step(table, batch, key)
    else:
        table, cons, asg = step(table, batch, key, constraints)
    return table, cons, asg


def sample_rows_for(nodes: int, score_pct: int, chunk: int) -> int | None:
    """percentageOfNodesToScore -> chunk-aligned window rows (None = the
    rounded window covers the whole table, i.e. scan everything)."""
    if score_pct >= 100:
        return None
    rows = -(-nodes * score_pct // 100)          # ceil
    rows = -(-rows // chunk) * chunk             # round up to chunk
    return None if rows >= nodes else rows


def sample_offset_for(i: int, nodes: int, rows: int) -> int:
    """Rotating window offset covering every row over ceil(N/S) steps
    (the tail window is anchored at N-S)."""
    w = nodes // rows
    total = w + (1 if nodes % rows else 0)
    i %= total
    return nodes - rows if i == w else i * rows


def mask_rows(table, row_mask):
    """A candidate-selection view where rows outside ``row_mask`` are
    infeasible on both backends: ``valid`` feeds the XLA filter chain and
    ``pods_alloc == 0`` is the fused kernel's row-validity convention.
    Commit state is untouched — binds land in the unmasked table."""
    if is_packed(table):
        return mask_rows_packed(table, row_mask)
    return table.replace(
        valid=table.valid & row_mask,
        pods_alloc=jnp.where(row_mask, table.pods_alloc, 0),
    )


@functools.lru_cache(maxsize=256)
def _jitted_schedule_packed(
    profile: Profile, chunk: int, k: int, with_constraints: bool,
    backend: str, pod_spec, table_spec, groups: frozenset,
    sample_rows: int | None, with_mask: bool = False,
    donate: bool = False, stratum_bits: int = 0,
):
    from k8s1m_tpu.snapshot.pod_encoding import unpack_pod_batch

    # Waves whose pods carry no selectors skip the affinity stage of the
    # fused kernel entirely; the packed field groups already say so.
    aff = bool(groups & {"sel", "req", "pref"})

    def impl(table, ints, bools, key, offset, row_mask, constraints):
        batch = unpack_pod_batch(ints, bools, pod_spec, table_spec, groups)
        src = table if row_mask is None else mask_rows(table, row_mask)
        if sample_rows is None:
            table, cons, asg = _schedule_batch_impl(
                table, batch, key, constraints, profile, chunk, k, backend,
                with_affinity=aff,
                src=None if row_mask is None else src,
                stratum_bits=stratum_bits,
            )
        else:
            # percentageOfNodesToScore: filter+score only a rotating
            # window of the node table (the reference's production
            # config scores 5% of nodes per pod at 1M scale —
            # terraform/tfvars percentageOfNodesToScore: 5,
            # README.adoc:525-531); the bind commit still lands in the
            # full table.  Candidate rows are remapped from window-local
            # to global.
            view = jax.tree.map(
                lambda a: lax.dynamic_slice_in_dim(a, offset, sample_rows, 0),
                src,
            )
            if backend == "pallas":
                from k8s1m_tpu.ops.pallas_topk import pallas_candidates

                p_stats = None
                view_cons = None
                if constraints is not None:
                    # Same composition rule as the XLA branch below:
                    # global domain statistics, window-local node cols.
                    from k8s1m_tpu.snapshot.constraints import (
                        slice_constraints,
                    )

                    p_stats = _prologue_stats(table, constraints)
                    view_cons = slice_constraints(
                        constraints, offset, sample_rows
                    )
                cand = pallas_candidates(
                    view, batch, key, profile, chunk=chunk, k=k,
                    with_affinity=aff,
                    constraints=view_cons, stats=p_stats,
                    stratum_bits=stratum_bits,
                )
            else:
                stats = None
                view_cons = None
                if constraints is not None:
                    # Constraint plugins under sampling: domain statistics
                    # are GLOBAL reductions over the full count tables
                    # (the prologue never depended on the scan window);
                    # only the per-node count columns follow the window.
                    from k8s1m_tpu.snapshot.constraints import (
                        slice_constraints,
                    )

                    stats = _prologue_stats(table, constraints)
                    view_cons = slice_constraints(
                        constraints, offset, sample_rows
                    )
                cand = filter_score_topk(
                    view, batch, key, profile, chunk=chunk, k=k,
                    constraints=view_cons, stats=stats,
                    stratum_bits=stratum_bits,
                )
            cand = cand.replace(
                idx=jnp.where(cand.idx >= 0, cand.idx + offset, -1)
            )
            table, cons, asg = finalize_batch(
                table, constraints, cand, commit_fields_of(batch)
            )
        # One fetchable result array: the bound node row per pod, -1 for
        # unbound.  Through a remote device relay every device_get is a
        # round trip; the coordinator reads this single array per wave.
        rows = jnp.where(asg.bound, asg.node_row, -1).astype(jnp.int32)
        return table, cons, asg, rows

    if with_constraints and with_mask:
        fn = impl
    elif with_constraints:
        fn = lambda table, ints, bools, key, offset, constraints: impl(
            table, ints, bools, key, offset, None, constraints
        )
    elif with_mask:
        fn = lambda table, ints, bools, key, offset, row_mask: impl(
            table, ints, bools, key, offset, row_mask, None
        )
    else:
        fn = lambda table, ints, bools, key, offset: impl(
            table, ints, bools, key, offset, None, None
        )
    if donate:
        # The production (coordinator) executable: the input table's —
        # and constraint state's — buffers are donated, so the wave's
        # commit_binds/constraint commit update HBM in place instead of
        # copy-on-write.  Callers MUST drop their reference (the
        # coordinator reassigns self.table from the return): a donated
        # array is deleted, and stale host references raise.
        donate_idx = (0, 6) if (with_constraints and with_mask) else (
            (0, 5) if with_constraints else (0,)
        )
        return jax.jit(fn, donate_argnums=donate_idx)
    # Replay/differential callers (tests, oracle comparisons, bench A/B
    # lanes) re-run the same input table; donation would delete it.
    return jax.jit(fn)  # graftlint: disable=undonated-device-update (non-donating replay variant; production passes donate=True)


def schedule_batch_packed(
    table,
    packed,
    key: jax.Array,
    *,
    profile: Profile,
    constraints: ConstraintState | None = None,
    chunk: int = 16384,
    k: int = 4,
    backend: str = "xla",
    sample_rows: int | None = None,
    sample_offset: int = 0,
    row_mask=None,
    mesh=None,
    donate: bool = False,
    stratum_bits: int = 0,
):
    """schedule_batch over a PackedPodBatch: the pod features cross the
    host->device boundary as two buffers and the bind decision comes back
    as one i32[B] row array (-1 = unbound) — 3 transfers per cycle total
    instead of ~40, which is what the per-call cost of a remote device
    relay demands.

    ``mesh`` (a (dp, sp) jax.sharding.Mesh) routes the step through
    parallel/sharded_cycle.make_sharded_packed_step: the table must be
    placed with its rows sharded over ``sp`` and ``sample_rows`` /
    ``sample_offset`` become SHARD-LOCAL (each shard scores a rotating
    window of its own rows).  Mutually exclusive with ``row_mask``
    (node-space process sharding and mesh sharding are different axes
    of scale-out; compose them across processes, not inside one step).

    ``sample_rows``/``sample_offset`` implement percentageOfNodesToScore:
    only rows [offset, offset+sample_rows) are filtered+scored this cycle
    (the caller rotates the offset).  The offset is a traced scalar — no
    recompile per window.  Works with constraint state: domain statistics
    are global prologue reductions over the full count tables, so only
    the per-node count columns follow the window (the reference's
    production config runs the full plugin set at pct 5 the same way,
    dist-scheduler.tf:551-570).

    ``row_mask`` (bool[N] device array) restricts candidate selection to
    the masked rows — the node-space sharding predicate of a scheduler
    shard set (control/shardset.py): every shard holds the full table,
    ownership is a mask, rebalancing flips mask bits instead of moving
    table data.  Traced, so reassignment never recompiles.

    ``donate=True`` donates the table's (and constraint state's) buffers
    to the step so the per-wave commit is in-place in HBM instead of
    copy-on-write — the production coordinator path, on BOTH execution
    paths: the single-device step and the mesh step donate alike (the
    sharded executables pin their out_specs, so each shard's buffers
    alias in place).  The caller's input references are DEAD afterwards
    (reassign from the return value); replay/differential callers that
    re-run the same table must keep the default.

    ``table`` may be a snapshot.packing.PackedNodeTable (the packed
    production layout): chunks decode on-device inside the scan slice on
    both backends, and binds are byte-identical to the unpacked layout
    (tests/test_packing.py differential gate).

    Returns (new_table, new_constraints, Assignment, rows).
    """
    if backend == "pallas" and constraints is None:
        from k8s1m_tpu.ops import pallas_topk

        if not pallas_topk.supports(profile):
            raise ValueError(
                "profile enables constraint plugins but no constraint "
                "state was passed (see ops/pallas_topk.py)"
            )
    if mesh is not None:
        if row_mask is not None:
            raise ValueError("mesh and row_mask are mutually exclusive")
        from k8s1m_tpu.parallel.sharded_cycle import make_sharded_packed_step

        step = make_sharded_packed_step(
            mesh, profile, chunk=chunk, k=k,
            pod_spec=packed.spec, table_spec=packed.table_spec,
            groups=packed.groups, sample_rows=sample_rows, backend=backend,
            donate=donate, stratum_bits=stratum_bits,
        )
        offset = np.int32(sample_offset)
        if constraints is not None:
            return step(
                table, packed.ints, packed.bools, key, offset, constraints
            )
        return step(table, packed.ints, packed.bools, key, offset)
    step = _jitted_schedule_packed(
        profile, chunk, k, constraints is not None, backend,
        packed.spec, packed.table_spec, packed.groups, sample_rows,
        row_mask is not None, donate, stratum_bits,
    )
    offset = np.int32(sample_offset)
    args = (table, packed.ints, packed.bools, key, offset)
    if row_mask is not None:
        args += (row_mask,)
    if constraints is not None:
        args += (constraints,)
    return step(*args)


# ---- deltasched: the plane-cached wave (engine/deltacache.py) -------------


@functools.lru_cache(maxsize=256)
def _jitted_schedule_delta(
    profile: Profile, chunk: int, k: int,
    pod_spec, table_spec, groups: frozenset, n_inflight: int,
    donate: bool = False, backend: str = "xla", stratum_bits: int = 0,
    index_k: int = 0, index_dirty_cap: int = 0,
):
    """The delta-wave executable: merge the dirty slice into the cached
    planes, hashed top-k over the merged planes, payload gather, shared
    greedy/commit epilogue.  Byte-identical to _jitted_schedule_packed
    for the same wave whenever the planes equal a full recompute of the
    un-dirty rows (the deltacache invalidation contract; gated by
    tests/test_deltasched.py).  Constraint state is deliberately not
    threaded: delta waves carry only constraint-termless pods, whose
    commit increments are identically zero.

    ``backend="pallas"`` runs the merged-plane top-k tail through the
    fused pallas kernel (ops/pallas_topk.delta_plane_topk) — the dirty
    gather/scatter-merge prolog is O(dirty) and stays XLA either way.

    ``index_k > 0`` threads the score-stratified candidate index
    through the step: the dirty slice updates the per-slot index
    in-step, a device-side ``lax.cond`` on index_usable picks between
    the O(K·batch) index tail and the O(N·batch) plane tail (which
    rebuilds the used slots' indexes from the merged planes), and the
    step reports which path ran as an extra i32 flag.  A dirty vector
    wider than ``index_dirty_cap`` skips the in-step update entirely —
    the cutoff is a trace-time SHAPE decision, so oversized waves
    compile the plane-only variant with no dead index code."""
    from k8s1m_tpu.engine.deltacache import (
        attach_payload,
        combine_dirty,
        dedup_rows,
        index_topk,
        index_usable,
        merge_dirty_planes,
        plane_topk,
        rebuild_index,
        update_index,
    )
    from k8s1m_tpu.snapshot.pod_encoding import unpack_pod_batch

    def impl(table, ints, bools, key, slot_ids, pmask, pscore, dirty,
             *rest):
        if index_k:
            rep_idx, rebuild_slots, idx_row, idx_class, idx_floor = rest[:5]
            inflight = rest[5:]
        else:
            inflight = rest
        batch = unpack_pod_batch(ints, bools, pod_spec, table_spec, groups)
        n = pmask.shape[1]
        rows = combine_dirty(dirty, inflight, n)
        pmask, pscore, mask_d, score_d = merge_dirty_planes(
            table, batch, profile, slot_ids, pmask, pscore, rows
        )
        seed = seed_of(key)

        def plane_tail():
            if backend == "pallas":
                from k8s1m_tpu.ops.pallas_topk import delta_plane_topk

                return delta_plane_topk(
                    pmask, pscore, slot_ids, seed, chunk=chunk, k=k,
                    stratum_bits=stratum_bits,
                )
            return plane_topk(
                pmask, pscore, slot_ids, seed, chunk=chunk, k=k,
                stratum_bits=stratum_bits,
            )

        flag = jnp.int32(0)
        if index_k and rows.shape[0] <= index_dirty_cap:
            rows_dd = dedup_rows(rows, n)
            idx_row, idx_class, idx_floor = update_index(
                idx_row, idx_class, idx_floor, rep_idx, rows_dd,
                mask_d, score_d, n, stratum_bits=stratum_bits,
            )
            usable = index_usable(idx_class, idx_floor, slot_ids, k)

            def from_index(state):
                ir, ic, fl = state
                return (
                    index_topk(
                        ir, ic, slot_ids, seed, k=k,
                        stratum_bits=stratum_bits,
                    ),
                    ir, ic, fl,
                )

            def from_planes(state):
                ir, ic, fl = state
                ir, ic, fl = rebuild_index(
                    pmask, pscore, rebuild_slots, rep_idx, ir, ic, fl,
                    chunk=chunk, stratum_bits=stratum_bits,
                    batch_b=slot_ids.shape[0],
                )
                return plane_tail(), ir, ic, fl

            cand, idx_row, idx_class, idx_floor = lax.cond(
                usable, from_index, from_planes,
                (idx_row, idx_class, idx_floor),
            )
            flag = usable.astype(jnp.int32)
        elif index_k:
            # Oversized dirty slice: plane tail, and the used slots'
            # indexes rebuild from the merged planes (or fail closed).
            cand = plane_tail()
            idx_row, idx_class, idx_floor = rebuild_index(
                pmask, pscore, rebuild_slots, rep_idx,
                idx_row, idx_class, idx_floor,
                chunk=chunk, stratum_bits=stratum_bits,
                batch_b=slot_ids.shape[0],
            )
        else:
            cand = plane_tail()
        cand = attach_payload(table, cand)
        table, _cons, asg = finalize_batch(
            table, None, cand, commit_fields_of(batch)
        )
        rows_out = jnp.where(asg.bound, asg.node_row, -1).astype(jnp.int32)
        if index_k:
            return (table, asg, rows_out, flag, pmask, pscore,
                    idx_row, idx_class, idx_floor)
        return table, asg, rows_out, pmask, pscore

    if donate:
        # Production form: the table, both plane buffers AND the index
        # buffers donate — the scatter-merge and index update rewrite
        # HBM in place, exactly like the wave's bind commit updates the
        # table.
        if index_k:
            return jax.jit(impl, donate_argnums=(0, 5, 6, 10, 11, 12))
        return jax.jit(impl, donate_argnums=(0, 5, 6))
    return jax.jit(impl)  # graftlint: disable=undonated-device-update (replay/differential variant; production passes donate=True)


def schedule_batch_delta(
    table,
    packed,
    key: jax.Array,
    *,
    profile: Profile,
    slot_ids,
    planes,
    dirty,
    inflight_rows=(),
    chunk: int = 16384,
    k: int = 4,
    mesh=None,
    donate: bool = False,
    backend: str = "xla",
    stratum_bits: int = 0,
    index=None,
    rep_idx=None,
    rebuild_slots=None,
    index_dirty_cap: int = 0,
):
    """schedule_batch_packed's delta-wave twin (deltasched): every pod's
    feasibility/score plane is already cached, so the device step runs
    the full kernel only over ``dirty`` ∪ the in-flight waves' bind rows
    and re-derives candidates from the merged planes.

    ``planes`` is the (mask, score) pair from the epoch-checked
    ``DeltaPlaneCache.planes`` accessor; ``slot_ids`` maps each batch
    position to its shape's plane slot (sentinel = slot count for
    padding); ``dirty`` is the sentinel-padded journaled dirty-row
    vector and ``inflight_rows`` the unretired waves' device-resident
    ``rows_dev`` arrays — consumed on-stream, never synced to host.

    ``index`` is the (idx_row, idx_class, idx_floor) triple from the
    epoch-checked ``DeltaPlaneCache.index_state`` accessor (with
    ``rep_idx``/``rebuild_slots`` from the WavePlan); when passed, the
    wave derives candidates from the candidate index whenever it is
    usable and the return grows to (new_table, Assignment, rows,
    new_planes, new_index, path_flag) — ``path_flag`` an i32 device
    scalar, 1 = index tail ran.  Without ``index`` the return stays
    (new_table, Assignment, rows, new_planes).

    Under ``mesh`` the planes must be sharded ``P(None, "sp")`` —
    row-sharded like every packed plane — the dirty gather stays
    shard-local, and the candidate index is unsupported (plane tail
    only).  ``backend="pallas"`` fuses the plane tail on either step.
    """
    pmask, pscore = planes
    if mesh is not None:
        if index is not None:
            raise ValueError(
                "the candidate index does not compose with mesh sharding"
            )
        from k8s1m_tpu.parallel.sharded_cycle import make_sharded_delta_step

        step = make_sharded_delta_step(
            mesh, profile, chunk=chunk, k=k,
            pod_spec=packed.spec, table_spec=packed.table_spec,
            groups=packed.groups, n_inflight=len(inflight_rows),
            donate=donate, backend=backend, stratum_bits=stratum_bits,
        )
        table, asg, rows, pmask, pscore = step(
            table, packed.ints, packed.bools, key, slot_ids, pmask,
            pscore, dirty, *inflight_rows,
        )
        return table, asg, rows, (pmask, pscore)
    index_k = 0 if index is None else index[0].shape[1]
    step = _jitted_schedule_delta(
        profile, chunk, k, packed.spec, packed.table_spec,
        packed.groups, len(inflight_rows), donate, backend, stratum_bits,
        index_k, index_dirty_cap,
    )
    if index is None:
        table, asg, rows, pmask, pscore = step(
            table, packed.ints, packed.bools, key, slot_ids, pmask,
            pscore, dirty, *inflight_rows,
        )
        return table, asg, rows, (pmask, pscore)
    table, asg, rows, flag, pmask, pscore, ir, ic, fl = step(
        table, packed.ints, packed.bools, key, slot_ids, pmask, pscore,
        dirty, rep_idx, rebuild_slots, *index, *inflight_rows,
    )
    return table, asg, rows, (pmask, pscore), (ir, ic, fl), flag


@functools.lru_cache(maxsize=64)
def _jitted_plane_fill(
    profile: Profile, chunk: int, pod_spec, table_spec, groups: frozenset
):
    """Plane-fill executable: one full filter+score pass for a batch of
    shape representatives, scattered into their plane slots.  The table
    is read-only here (fills never commit); only the plane buffers
    donate."""
    from k8s1m_tpu.engine.deltacache import fill_planes_scan
    from k8s1m_tpu.snapshot.pod_encoding import unpack_pod_batch

    def impl(table, ints, bools, fill_slots, pmask, pscore):
        batch = unpack_pod_batch(ints, bools, pod_spec, table_spec, groups)
        return fill_planes_scan(
            table, batch, profile, fill_slots, pmask, pscore, chunk=chunk
        )

    return jax.jit(impl, donate_argnums=(4, 5))


def fill_shape_planes(
    table,
    packed,
    fill_slots,
    planes,
    *,
    profile: Profile,
    chunk: int = 16384,
    mesh=None,
):
    """Populate the plane slots in ``fill_slots`` from a full pass for
    the representative pods in ``packed`` (deltasched cold-shape /
    refresh path).  Returns the new (mask, score) planes; the table is
    untouched and NOT donated."""
    pmask, pscore = planes
    if mesh is not None:
        from k8s1m_tpu.parallel.sharded_cycle import make_sharded_plane_fill

        fill = make_sharded_plane_fill(
            mesh, profile, chunk=chunk,
            pod_spec=packed.spec, table_spec=packed.table_spec,
            groups=packed.groups,
        )
    else:
        fill = _jitted_plane_fill(
            profile, chunk, packed.spec, packed.table_spec, packed.groups
        )
    return fill(table, packed.ints, packed.bools, fill_slots, pmask, pscore)
