"""deltasched: incremental filter+score via shape-keyed plane reuse.

The steady-state regime is heavy traffic at low churn: millions of
template-shaped pods per hour against a table whose rows barely move
(hotfeed's template hit rate is 1.0 at 90%-hot pools).  Yet every wave
recomputes filter+score over ALL N rows even when the pod's structural
shape was seen last wave and <0.1% of rows changed.  This module keeps,
per pod *shape* (snapshot/hotfeed.shape_key: structural fingerprint +
request scalars), the HBM-resident *plane* that pass produces — the
feasibility mask ``bool[N]`` and the pre-greedy integer score ``i32[N]``
— and lets a wave whose every pod hits the cache run the full kernel
only over the rows that actually moved:

    dirty rows (the coordinator's _dirty_rows/_dirty_caps scatters,
    retired bind commits, eviction repairs — journaled through
    snapshot/node_table.RowVersions)
  ∪ rows touched by in-flight binds (each unretired wave's device-
    resident ``rows_dev`` array, consumed on-stream — the host never
    syncs to learn them)

then scatter-merge the recomputed columns into the cached planes and
proceed straight to the per-pod hashed top-k over the merged plane.
Per-wave device work drops from O(batch × N × plugin-chain) toward
O(batch × dirty) plus a cheap O(batch × N) hash/top-k tail.

**The cache is an invisible replay, never a semantic.**  Binds must be
BYTE-IDENTICAL to full recompute under churn, pipelining, preemption,
gangs, mesh sharding and donation (tests/test_deltasched.py).  The
contract that makes that hold:

- a plane is keyed on ``(shape_key, vocab generation)``; pods whose
  mask/score reads the live constraint count tables (spread/affinity
  refs or incs) are NOT cacheable — their key is None and the wave
  takes the full pass (the constraint stage is an exact identity for
  termless pods, so delta waves may skip it entirely);
- row-level invalidation is version-journaled (RowVersions): every
  device-table row mutation is noted when its scatter/commit is
  *dispatched*, so a delta wave enqueued later recomputes those rows
  from the post-mutation table — stream order does the rest;
- capacity-delta rows and structural rows ride the same recompute
  (recomputing both planes for a dirty row is conservative and exact);
- vocab generation movement, packing rebuilds, resync and mesh/table
  rebuilds drop the cache WHOLESALE (``drop_all``) — those events
  change what encoded ids *mean*, which no row set can bound;
- HBM is bounded: a fixed slot count with LRU shape eviction
  (``deltasched_evictions_total``).

Sharding (parallel/sharded_cycle.make_sharded_delta_step): the planes
shard on ``sp`` along the row axis exactly like every packed table
plane; the dirty-slice gather and the plane top-k stay shard-local and
tie-breaks hash over global coordinates, so the mesh delta wave is
byte-identical to the single-device delta wave — which is byte-identical
to full recompute.

Host-side reads of the plane buffers outside this module MUST flow
through the epoch-checked accessor ``DeltaPlaneCache.planes(gen)``
(enforced statically by the ``deltacache-epoch-keyed`` graftlint pass):
raw attribute access would let a stale-generation plane reach a wave.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.plugins.registry import Profile, score_and_filter
from k8s1m_tpu.snapshot.node_table import RowVersions
from k8s1m_tpu.snapshot.packing import is_packed, unpack_chunk

log = logging.getLogger("k8s1m.deltasched")

_WAVES = Counter(
    "deltasched_waves_total",
    "Coordinator waves by execution path (delta = plane-cached step over "
    "the dirty slice; full = the ordinary full filter+score pass)",
    ("path",),
)
_SHAPE_HITS = Counter(
    "deltasched_shape_hits_total",
    "Per-pod shape lookups answered by a live cached plane", (),
)
_SHAPE_MISSES = Counter(
    "deltasched_shape_misses_total",
    "Per-pod shape lookups that missed (cold shape, evicted, "
    "generation-dropped, or an uncacheable constraint-coupled shape)",
    (),
)
_EVICTIONS = Counter(
    "deltasched_evictions_total",
    "Cached shape planes evicted by the LRU slot bound "
    "(the HBM-budget pressure signal)", (),
)
_FILLS = Counter(
    "deltasched_fills_total",
    "Plane fills dispatched (cold recurring shapes populated, or stale "
    "slots refilled after journal compaction / oversized dirty sets)", (),
)
_DIRTY_ROWS = Counter(
    "deltasched_dirty_rows_total",
    "Host-journaled dirty rows recomputed across delta waves (mean "
    "dirty fraction = this / (delta waves x table rows))", (),
)
_PLANES_RESIDENT = Gauge(
    "deltasched_planes_resident",
    "Shape planes currently resident across live delta caches", (),
)
_LIVE_CACHES: weakref.WeakSet = weakref.WeakSet()
_PLANES_RESIDENT.set_function(
    lambda: sum(len(c._slot_of) for c in _LIVE_CACHES)
)


def resolve_deltasched(arg: str | bool | None = None) -> str:
    """Delta-cache mode from an explicit arg or the K8S1M_DELTASCHED env
    var.  Returns "off" or "on"; unknown values fail loudly (a typo'd
    env var silently running full recompute would invalidate every
    steady-state number downstream)."""
    if isinstance(arg, bool):
        return "on" if arg else "off"
    mode = arg if arg is not None else os.environ.get("K8S1M_DELTASCHED", "off")
    if mode not in ("off", "on"):
        raise ValueError(
            f"K8S1M_DELTASCHED/deltacache must be off|on, got {mode!r}"
        )
    return mode


# ---- device-side plane ops (traced inside the delta/fill executables) ----


def combine_dirty(host_dirty, inflight_rows, sentinel: int):
    """One global dirty-row vector: the host-journaled rows (already
    sentinel-padded) plus every in-flight wave's bind rows, with their
    -1 unbound markers remapped to the out-of-bounds sentinel so the
    scatter-merge drops them."""
    parts = [host_dirty]
    for r in inflight_rows:
        parts.append(jnp.where(r >= 0, r, sentinel).astype(jnp.int32))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def gather_rows(table, idx):
    """A decoded mini-table of the rows at ``idx`` (clipped; callers
    drop out-of-range entries at scatter time).  A packed table decodes
    the gathered rows here — unpack_chunk is row-elementwise, so it
    applies to an arbitrary gathered row set just like a chunk slice."""
    n = table.num_rows
    safe = jnp.clip(idx, 0, n - 1)
    sub = jax.tree.map(lambda a: a[safe], table)
    return unpack_chunk(sub) if is_packed(sub) else sub


def merge_dirty_planes(
    table, batch, profile: Profile, slot_ids, pmask, pscore, rows
):
    """Recompute filter+score for ``rows`` against the CURRENT table and
    scatter-merge the columns into the cached planes at each pod's slot.

    ``rows`` are plane-local (shard-local on the mesh) with the
    out-of-bounds sentinel for padding/unowned entries; ``slot_ids``
    carry the slot-count sentinel for padded pods.  Duplicate (slot,
    row) targets always carry identical values — two pods share a slot
    only when they share the full shape key, and a row listed twice
    recomputes the same column — so the scatter is deterministic.

    Constraints are deliberately absent: a delta wave only ever carries
    constraint-termless pods, for which the constraint stage is an
    exact identity (plugins/topology.filter_and_score masks nothing and
    scores zero when no term is valid).
    """
    mask_d, score_d = score_and_filter(
        gather_rows(table, rows), batch, profile, None, None
    )
    at = (slot_ids[:, None], rows[None, :])
    pmask = pmask.at[at].set(mask_d, mode="drop")
    pscore = pscore.at[at].set(score_d, mode="drop")
    return pmask, pscore


def plane_topk(
    pmask, pscore, slot_ids, seed, *, chunk: int, k: int,
    row_offset=0, pod_offset=0,
):
    """Per-pod hashed top-k over the merged planes — the delta wave's
    replacement for the full filter+score chunk scan.

    Mirrors engine/cycle.filter_score_topk's scan EXACTLY (same chunk
    walk, same pack_hashed jitter over global (pod row, node column)
    coordinates, same merge_topk carry) so the surviving candidates are
    bit-identical to the full pass over an equal mask/score field —
    the byte-identity contract's tail half.  Payload columns come back
    zeroed; ``attach_payload`` gathers them from the live table (the
    values are gated by feasibility downstream, so end-gather equals
    the full pass's per-chunk gather byte-for-byte).
    """
    from k8s1m_tpu.engine.cycle import (
        Candidates,
        chunk_topk,
        empty_candidates,
        merge_topk,
    )
    from k8s1m_tpu.ops.priority import pack_hashed

    n = pmask.shape[1]
    if n % chunk:
        raise ValueError(f"plane rows {n} not divisible by chunk {chunk}")
    num_chunks = n // chunk
    b = slot_ids.shape[0]
    pod_rows = lax.broadcasted_iota(jnp.int32, (b, 1), 0) + pod_offset
    zeros = jnp.zeros((b, k), jnp.int32)

    def body(carry, _):
        carry, ci = carry
        start = ci * chunk
        m = jnp.take(
            lax.dynamic_slice_in_dim(pmask, start, chunk, 1), slot_ids, 0
        )
        sc = jnp.take(
            lax.dynamic_slice_in_dim(pscore, start, chunk, 1), slot_ids, 0
        )
        node_cols = (
            lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
            + start + row_offset
        )
        prio = pack_hashed(sc, seed, m, pod_rows, node_cols)
        top_prio, idx = chunk_topk(prio, k)
        local = Candidates(
            idx=(idx + start + row_offset).astype(jnp.int32),
            prio=top_prio,
            cpu=zeros, mem=zeros, pods=zeros, zone=zeros, region=zeros,
        )
        return (merge_topk(carry, local, k), ci + 1), None

    init = (empty_candidates(b, k), jnp.int32(0))
    if num_chunks == 1:
        (cand, _), _ = body(init, None)
    else:
        (cand, _), _ = lax.scan(body, init, None, length=num_chunks)
    return cand.replace(idx=jnp.where(cand.prio >= 0, cand.idx, -1))


def attach_payload(table, cand, row_offset=0):
    """Gather the candidate payload (free capacity at batch start,
    topology domains) from the live table at the surviving top-k rows.

    The full pass gathers these per chunk during the scan; the table
    does not change within a step, so gathering at the end reads the
    identical values — and infeasible candidates' payload (clipped
    garbage) is unread downstream (greedy_assign gates on prio >= 0,
    the assignment gates on bound)."""
    local = cand.idx - row_offset
    sub = gather_rows(table, local.reshape(-1))
    free_cpu, free_mem, free_pods = sub.free()
    shape = cand.idx.shape
    return cand.replace(
        cpu=free_cpu.reshape(shape),
        mem=free_mem.reshape(shape),
        pods=free_pods.reshape(shape),
        zone=sub.zone.reshape(shape),
        region=sub.region.reshape(shape),
    )


def fill_planes_scan(
    table, batch, profile: Profile, fill_slots, pmask, pscore, *, chunk: int
):
    """Populate plane rows for a batch of shape representatives: one
    full chunked filter+score pass over the (shard-local) table, each
    chunk's columns scattered into the representatives' slots.  The
    sentinel slot (out of bounds) drops padded representatives."""
    from k8s1m_tpu.engine.cycle import _slice_table

    n = pmask.shape[1]
    if n % chunk:
        raise ValueError(f"plane rows {n} not divisible by chunk {chunk}")
    num_chunks = n // chunk

    def body(carry, _):
        pmask, pscore, ci = carry
        start = ci * chunk
        tchunk = _slice_table(table, start, chunk)
        mask, score = score_and_filter(tchunk, batch, profile, None, None)
        cols = start + lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        at = (fill_slots[:, None], cols)
        pmask = pmask.at[at].set(mask, mode="drop")
        pscore = pscore.at[at].set(score, mode="drop")
        return (pmask, pscore, ci + 1), None

    init = (pmask, pscore, jnp.int32(0))
    if num_chunks == 1:
        (pmask, pscore, _), _ = body(init, None)
    else:
        (pmask, pscore, _), _ = lax.scan(body, init, None, length=num_chunks)
    return pmask, pscore


# ---- host-side cache controller -------------------------------------------


@dataclasses.dataclass
class WavePlan:
    """One wave's delta decision (DeltaPlaneCache.plan).

    ``fill_idx``/``fill_slots`` name the batch positions whose shapes
    must be plane-filled BEFORE the wave dispatches (recurring shapes
    being promoted, or stale slots being refreshed) — the coordinator
    encodes those representatives and runs the fill executable whether
    or not the wave itself goes delta.  ``slot_ids`` is None for a full
    wave (some shape stayed unresolvable); otherwise the wave runs the
    delta step with ``dirty`` (sentinel-padded global rows) and the
    stamps in ``stamp_slots`` applied at commit time."""

    fill_idx: list[int]
    fill_slots: list[int]
    slot_ids: np.ndarray | None = None
    dirty: np.ndarray | None = None
    stamp_slots: tuple[int, ...] = ()
    stamp_ver: int = 0


class DeltaPlaneCache:
    """Host controller of the HBM-resident per-shape plane cache.

    Owns the device plane buffers (``bool[S, N]`` mask + ``i32[S, N]``
    score, sharded over ``sp`` on the row axis under a mesh), the shape
    key → slot map with LRU eviction, the per-slot freshness stamps,
    and the row-version journal consumers invalidate through.  All
    state is cycle-thread-confined, like the dirty-row sets it mirrors.
    """

    def __init__(
        self,
        num_rows: int,
        *,
        slots: int = 64,
        fill_batch: int = 16,
        journal_cap: int | None = None,
        seen_cap: int = 1 << 16,
        dirty_cap: int | None = None,
        sharding=None,
    ) -> None:
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.num_rows = num_rows
        self.slots = slots
        self.fill_batch = fill_batch
        # Past this many dirty rows the delta recompute stops being a
        # bargain; the plan refreshes the used slots wholesale instead
        # (a fill is one F-pod pass, far cheaper than a B-pod full wave)
        # and the wave still runs delta over an empty dirty set.
        self.dirty_cap = (
            dirty_cap if dirty_cap is not None else max(num_rows // 4, 1)
        )
        if journal_cap is None:
            # Scale-aware journal bound (ISSUE 14): the cap tracks the
            # TABLE SIZE, not a fixed row budget — at the old 1<<16 a
            # 1M-row churn burst compacted the journal every wave and
            # fail-closed the whole cache to wholesale refills.  Half
            # the table (compacting down to dirty_cap, a quarter) keeps
            # the enumerable window a constant FRACTION of rows: the
            # delta lane stays plannable right up to the dirty_cap
            # break-even it would abandon anyway.  At 131072 rows this
            # derives exactly the old 1<<16 — the fixed-cap
            # differential gate (tests/test_megarow.py).
            journal_cap = max(1 << 16, num_rows // 2)
        self.versions = RowVersions(cap=journal_cap)
        self._sharding = sharding
        self._mask = None           # bool[S, N] device plane
        self._score = None          # i32[S, N] device plane
        self._slot_of: collections.OrderedDict = collections.OrderedDict()
        self._free: list[int] = list(range(slots - 1, -1, -1))
        self._fresh: dict[int, int] = {}     # slot -> version stamp
        self._gen = -1                       # vocab generation of planes
        # Shapes seen once before (promotion gate: a shape plane-fills
        # only on its SECOND sighting, so one-shot shapes — the cold/
        # high-churn lane — never pay a fill).  Bounded like the
        # coordinator's _gang_oversize set: clearing just re-requires
        # one extra sighting from a repeat shape.
        self._seen: set = set()
        self._seen_cap = seen_cap
        _LIVE_CACHES.add(self)

    # -- device buffers ---------------------------------------------------

    def ensure_device(self) -> None:
        if self._mask is not None:
            return
        s, n = self.slots, self.num_rows
        mask = jnp.zeros((s, n), jnp.bool_)
        score = jnp.zeros((s, n), jnp.int32)
        if self._sharding is not None:
            mask = jax.device_put(mask, self._sharding)
            score = jax.device_put(score, self._sharding)
        self._mask, self._score = mask, score

    def planes(self, gen: int):
        """THE epoch-checked plane accessor (deltacache-epoch-keyed
        lint contract): hands out the device buffers only against the
        generation they were computed at.  A mismatch is a caller bug —
        the cache must be generation-checked (check_generation) before
        any wave planning touches it."""
        if gen != self._gen:
            raise RuntimeError(
                f"delta plane access at generation {gen} but planes are "
                f"stamped {self._gen}; call check_generation first"
            )
        self.ensure_device()
        return self._mask, self._score

    def commit(self, mask, score, plan: WavePlan | None = None) -> None:
        """Store the (donated-through) plane buffers back and apply the
        plan's freshness stamps — called only after the dispatch that
        consumed the old buffers succeeded."""
        self._mask, self._score = mask, score
        if plan is not None:
            for s in plan.stamp_slots:
                self._fresh[s] = plan.stamp_ver

    # -- invalidation -----------------------------------------------------

    def note_rows(self, rows) -> None:
        """Journal one batch of device-table row mutations (called when
        the mutating scatter/commit is DISPATCHED, so stream order
        guarantees later delta waves recompute from the new values)."""
        if self._slot_of or self._seen:
            self.versions.note(rows)

    def check_generation(self, gen: int) -> None:
        """Drop everything when the vocab generation moved: cached
        planes bake interned ids (tolerated taint sets, selector value
        ids), and a new id can change what an identical shape encodes."""
        if gen != self._gen:
            if self._slot_of:
                self.drop_all("generation")
            self._gen = gen

    def drop_all(self, reason: str) -> None:
        """Wholesale invalidation: table rebuilds (packing widening,
        mesh/device re-upload), resync, vocab generation movement.  The
        device buffers stay allocated — only the host keying drops, so
        the next fills simply overwrite."""
        if self._slot_of:
            log.info(
                "deltasched: dropping %d cached shape planes (%s)",
                len(self._slot_of), reason,
            )
        self._free = list(range(self.slots - 1, -1, -1))
        self._slot_of.clear()
        self._fresh.clear()
        self._seen.clear()
        # Everything before this point is unenumerable by construction.
        self.versions.release(self.versions.ver + 1)

    def reset(self, reason: str) -> None:
        """drop_all PLUS discard the device buffers (a failed donating
        dispatch leaves them in an unknown consumed state); the next
        ensure_device reallocates zeros."""
        self.drop_all(reason)
        self._mask = self._score = None

    # -- wave planning ----------------------------------------------------

    def _note_seen(self, key) -> None:
        if len(self._seen) >= self._seen_cap:
            self._seen.clear()
        self._seen.add(key)

    def _alloc_slot(self, key, busy) -> int | None:
        """A slot for ``key``: a free one, else LRU-evict — but NEVER a
        slot in ``busy`` (already assigned to a pod of the CURRENT
        wave): evicting one would refill it with this key's plane and
        the earlier pod would silently read the wrong shape's mask/
        score — a byte-identity break with no error.  Returns None when
        every resident slot is busy (the wave takes the full pass)."""
        if self._free:
            slot = self._free.pop()
        else:
            victim = next(
                (
                    (k, s) for k, s in self._slot_of.items()  # LRU first
                    if s not in busy
                ),
                None,
            )
            if victim is None:
                return None
            del self._slot_of[victim[0]]
            slot = victim[1]
            self._fresh.pop(slot, None)
            _EVICTIONS.inc()
        self._slot_of[key] = slot
        return slot

    def plan(self, keys, batch_b: int) -> WavePlan:
        """Decide this wave's path from the pods' shape keys.

        ``keys`` is one entry per real pod (None = uncacheable shape);
        ``batch_b`` is the encoded batch size (padding gets the slot
        sentinel).  Returns a WavePlan: fills to dispatch first, and —
        when every shape resolved to a live slot — the delta step's
        slot ids, sentinel-padded dirty rows, and commit stamps.
        """
        fills_idx: list[int] = []
        fills_slot: list[int] = []
        if any(k is None for k in keys):
            # Constraint-coupled shapes poison the whole wave (their
            # pods need the real constraint stage); no fills either —
            # mixed waves are the cold lane, keep it zero-overhead.
            _SHAPE_MISSES.inc(len(keys))
            _WAVES.inc(path="full")
            return WavePlan([], [])
        slot_ids = np.full(batch_b, self.slots, np.int32)
        hits = misses = 0
        missing = False
        filled_keys: dict = {}
        busy: set[int] = set()   # slots assigned to THIS wave so far
        for i, key in enumerate(keys):
            slot = self._slot_of.get(key)
            if slot is not None:
                self._slot_of.move_to_end(key)
                slot_ids[i] = slot
                busy.add(slot)
                hits += 1
                continue
            misses += 1
            prior = filled_keys.get(key)
            if prior is not None:
                slot_ids[i] = prior
                continue
            if key in self._seen and len(fills_idx) < self.fill_batch:
                slot = self._alloc_slot(key, busy)
                if slot is None:
                    # Every resident slot belongs to a pod of this very
                    # wave: no evictable victim.  Full pass.
                    missing = True
                    continue
                fills_idx.append(i)
                fills_slot.append(slot)
                filled_keys[key] = slot
                slot_ids[i] = slot
                busy.add(slot)
            else:
                self._note_seen(key)
                missing = True
        _SHAPE_HITS.inc(hits)
        if misses:
            _SHAPE_MISSES.inc(misses)
        if missing:
            _WAVES.inc(path="full")
            return WavePlan(fills_idx, fills_slot)

        # Dirty slice: rows mutated since the stalest used slot's fill.
        used = sorted({int(s) for s in slot_ids if s < self.slots})
        fresh_fills = set(fills_slot)
        stale = [
            s for s in used
            if s not in fresh_fills
            and self._fresh.get(s, -1) < self.versions.floor
        ]
        dirty: set[int] | None = set()
        live = [s for s in used if s not in fresh_fills and s not in stale]
        if live:
            vmin = min(self._fresh[s] for s in live)
            dirty = self.versions.rows_since(vmin)
        if dirty is None or len(dirty) > self.dirty_cap or stale:
            # Unenumerable or oversized delta (journal compaction, a
            # churn burst): refresh every used slot wholesale — one
            # F-shape fill pass — and run delta over the in-flight rows
            # alone.  Slots past the fill budget force the full pass.
            refresh = [s for s in used if s not in fresh_fills]
            if len(fills_idx) + len(refresh) > self.fill_batch:
                _WAVES.inc(path="full")
                return WavePlan(fills_idx, fills_slot)
            slot_at = {int(s): i for i, s in enumerate(slot_ids) if s < self.slots}
            for s in refresh:
                fills_idx.append(slot_at[s])
                fills_slot.append(s)
            dirty = set()
        _WAVES.inc(path="delta")
        _DIRTY_ROWS.inc(len(dirty))
        return WavePlan(
            fills_idx, fills_slot,
            slot_ids=slot_ids,
            dirty=self._pad_dirty(dirty),
            stamp_slots=tuple(used),
            stamp_ver=self.versions.ver,
        )

    def _pad_dirty(self, rows: set) -> np.ndarray:
        """Sorted, power-of-two-padded dirty rows with the out-of-bounds
        sentinel (= num_rows) as padding, so the jitted step sees a
        handful of shapes instead of one trace per dirty count."""
        arr = np.fromiter(rows, np.int32, len(rows))
        arr.sort()
        cap = 1 << max(0, int(max(arr.size, 1) - 1).bit_length())
        out = np.full(cap, self.num_rows, np.int32)
        out[: arr.size] = arr
        return out

    def note_fill(self, plan: WavePlan) -> None:
        """Stamp freshly-filled slots at the journal version their fill
        dispatch observed (called right after the fill executable is
        enqueued)."""
        _FILLS.inc(len(plan.fill_slots))
        for s in plan.fill_slots:
            self._fresh[s] = self.versions.ver

    def abort_fills(self, plan: WavePlan) -> None:
        """Un-allocate the plan's fill slots (the representative encode
        failed, e.g. a query-key overflow across fill shapes): the keys
        drop back to seen-once and the wave takes the full pass."""
        for s in plan.fill_slots:
            self._fresh.pop(s, None)
            self._free.append(s)
        for key, slot in list(self._slot_of.items()):
            if slot in set(plan.fill_slots):
                del self._slot_of[key]
        plan.fill_idx.clear()
        plan.fill_slots.clear()

    @property
    def resident(self) -> int:
        return len(self._slot_of)
