"""deltasched: incremental filter+score via shape-keyed plane reuse.

The steady-state regime is heavy traffic at low churn: millions of
template-shaped pods per hour against a table whose rows barely move
(hotfeed's template hit rate is 1.0 at 90%-hot pools).  Yet every wave
recomputes filter+score over ALL N rows even when the pod's structural
shape was seen last wave and <0.1% of rows changed.  This module keeps,
per pod *shape* (snapshot/hotfeed.shape_key: structural fingerprint +
request scalars), the HBM-resident *plane* that pass produces — the
feasibility mask ``bool[N]`` and the pre-greedy integer score ``i32[N]``
— and lets a wave whose every pod hits the cache run the full kernel
only over the rows that actually moved:

    dirty rows (the coordinator's _dirty_rows/_dirty_caps scatters,
    retired bind commits, eviction repairs — journaled through
    snapshot/node_table.RowVersions)
  ∪ rows touched by in-flight binds (each unretired wave's device-
    resident ``rows_dev`` array, consumed on-stream — the host never
    syncs to learn them)

then scatter-merge the recomputed columns into the cached planes and
proceed straight to the per-pod hashed top-k over the merged plane.
Per-wave device work drops from O(batch × N × plugin-chain) toward
O(batch × dirty) plus a cheap O(batch × N) hash/top-k tail.

**The cache is an invisible replay, never a semantic.**  Binds must be
BYTE-IDENTICAL to full recompute under churn, pipelining, preemption,
gangs, mesh sharding and donation (tests/test_deltasched.py).  The
contract that makes that hold:

- a plane is keyed on ``(shape_key, vocab generation)``; pods whose
  mask/score reads the live constraint count tables (spread/affinity
  refs or incs) are NOT cacheable — their key is None and the wave
  takes the full pass (the constraint stage is an exact identity for
  termless pods, so delta waves may skip it entirely);
- row-level invalidation is version-journaled (RowVersions): every
  device-table row mutation is noted when its scatter/commit is
  *dispatched*, so a delta wave enqueued later recomputes those rows
  from the post-mutation table — stream order does the rest;
- capacity-delta rows and structural rows ride the same recompute
  (recomputing both planes for a dirty row is conservative and exact);
- vocab generation movement, packing rebuilds, resync and mesh/table
  rebuilds drop the cache WHOLESALE (``drop_all``) — those events
  change what encoded ids *mean*, which no row set can bound;
- HBM is bounded: a fixed slot count with LRU shape eviction
  (``deltasched_evictions_total``).

Sharding (parallel/sharded_cycle.make_sharded_delta_step): the planes
shard on ``sp`` along the row axis exactly like every packed table
plane; the dirty-slice gather and the plane top-k stay shard-local and
tie-breaks hash over global coordinates, so the mesh delta wave is
byte-identical to the single-device delta wave — which is byte-identical
to full recompute.

Host-side reads of the plane buffers outside this module MUST flow
through the epoch-checked accessor ``DeltaPlaneCache.planes(gen)``
(enforced statically by the ``deltacache-epoch-keyed`` graftlint pass):
raw attribute access would let a stale-generation plane reach a wave.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import os
import weakref

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from k8s1m_tpu.obs.metrics import Counter, Gauge
from k8s1m_tpu.plugins.registry import Profile, score_and_filter
from k8s1m_tpu.snapshot.node_table import RowVersions
from k8s1m_tpu.snapshot.packing import is_packed, unpack_chunk

log = logging.getLogger("k8s1m.deltasched")

_WAVES = Counter(
    "deltasched_waves_total",
    "Coordinator waves by execution path (delta = plane-cached step over "
    "the dirty slice; full = the ordinary full filter+score pass)",
    ("path",),
)
_SHAPE_HITS = Counter(
    "deltasched_shape_hits_total",
    "Per-pod shape lookups answered by a live cached plane", (),
)
_SHAPE_MISSES = Counter(
    "deltasched_shape_misses_total",
    "Per-pod shape lookups that missed (cold shape, evicted, "
    "generation-dropped, or an uncacheable constraint-coupled shape)",
    (),
)
_EVICTIONS = Counter(
    "deltasched_evictions_total",
    "Cached shape planes evicted by the LRU slot bound "
    "(the HBM-budget pressure signal)", (),
)
_FILLS = Counter(
    "deltasched_fills_total",
    "Plane fills dispatched (cold recurring shapes populated, or stale "
    "slots refilled after journal compaction / oversized dirty sets)", (),
)
_DIRTY_ROWS = Counter(
    "deltasched_dirty_rows_total",
    "Host-journaled dirty rows recomputed across delta waves (mean "
    "dirty fraction = this / (delta waves x table rows))", (),
)
_PLANES_RESIDENT = Gauge(
    "deltasched_planes_resident",
    "Shape planes currently resident across live delta caches", (),
)
_INDEX_WAVES = Counter(
    "deltasched_index_waves_total",
    "Delta waves by candidate-index outcome (index = per-pod candidates "
    "derived from the score-stratified top-K index, O(dirty + K*batch); "
    "plane = the index failed closed and the wave fell back to the full "
    "O(batch * N) merged-plane top-k scan)",
    ("path",),
)
_INDEX_DROPS = Counter(
    "deltasched_index_drops_total",
    "Candidate-index invalidations by cause: underflow = eviction-floor "
    "underflow (more candidates invalidated than K spares), "
    "oversized-dirty = the wave's dirty slice exceeded the in-step "
    "index-update budget, fill = slot (re)filled so its index must "
    "rebuild, plus every wholesale cache drop reason (generation / "
    "resync / packing / fill-error / dispatch-error)",
    ("reason",),
)
_INDEX_TOUCHED = Counter(
    "deltasched_index_touched_rows_total",
    "Rows the delta wave's candidate derivation actually visited, by "
    "path (index: dirty slice + K index entries; plane: the full N-row "
    "scan plus the dirty slice) — divide by deltasched_index_waves_total "
    "x table rows for the sublinearity ratio the index exists to buy",
    ("path",),
)
_LIVE_CACHES: weakref.WeakSet = weakref.WeakSet()
_PLANES_RESIDENT.set_function(
    lambda: sum(len(c._slot_of) for c in _LIVE_CACHES)
)


def resolve_deltasched(arg: str | bool | None = None) -> str:
    """Delta-cache mode from an explicit arg or the K8S1M_DELTASCHED env
    var.  Returns "off" or "on"; unknown values fail loudly (a typo'd
    env var silently running full recompute would invalidate every
    steady-state number downstream)."""
    if isinstance(arg, bool):
        return "on" if arg else "off"
    mode = arg if arg is not None else os.environ.get("K8S1M_DELTASCHED", "off")
    if mode not in ("off", "on"):
        raise ValueError(
            f"K8S1M_DELTASCHED/deltacache must be off|on, got {mode!r}"
        )
    return mode


# ---- device-side plane ops (traced inside the delta/fill executables) ----


def combine_dirty(host_dirty, inflight_rows, sentinel: int):
    """One global dirty-row vector: the host-journaled rows (already
    sentinel-padded) plus every in-flight wave's bind rows, with their
    -1 unbound markers remapped to the out-of-bounds sentinel so the
    scatter-merge drops them."""
    parts = [host_dirty]
    for r in inflight_rows:
        parts.append(jnp.where(r >= 0, r, sentinel).astype(jnp.int32))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def gather_rows(table, idx):
    """A decoded mini-table of the rows at ``idx`` (clipped; callers
    drop out-of-range entries at scatter time).  A packed table decodes
    the gathered rows here — unpack_chunk is row-elementwise, so it
    applies to an arbitrary gathered row set just like a chunk slice."""
    n = table.num_rows
    safe = jnp.clip(idx, 0, n - 1)
    sub = jax.tree.map(lambda a: a[safe], table)
    return unpack_chunk(sub) if is_packed(sub) else sub


def merge_dirty_planes(
    table, batch, profile: Profile, slot_ids, pmask, pscore, rows
):
    """Recompute filter+score for ``rows`` against the CURRENT table and
    scatter-merge the columns into the cached planes at each pod's slot.

    ``rows`` are plane-local (shard-local on the mesh) with the
    out-of-bounds sentinel for padding/unowned entries; ``slot_ids``
    carry the slot-count sentinel for padded pods.  Duplicate (slot,
    row) targets always carry identical values — two pods share a slot
    only when they share the full shape key, and a row listed twice
    recomputes the same column — so the scatter is deterministic.

    Constraints are deliberately absent: a delta wave only ever carries
    constraint-termless pods, for which the constraint stage is an
    exact identity (plugins/topology.filter_and_score masks nothing and
    scores zero when no term is valid).
    """
    mask_d, score_d = score_and_filter(
        gather_rows(table, rows), batch, profile, None, None
    )
    at = (slot_ids[:, None], rows[None, :])
    pmask = pmask.at[at].set(mask_d, mode="drop")
    pscore = pscore.at[at].set(score_d, mode="drop")
    # The recomputed columns come back alongside the merged planes: the
    # candidate-index update (update_index) keys on exactly these values
    # and recomputing them there would double the dirty gather.
    return pmask, pscore, mask_d, score_d


def plane_topk(
    pmask, pscore, slot_ids, seed, *, chunk: int, k: int,
    row_offset=0, pod_offset=0, stratum_bits: int = 0,
):
    """Per-pod hashed top-k over the merged planes — the delta wave's
    replacement for the full filter+score chunk scan.

    Mirrors engine/cycle.filter_score_topk's scan EXACTLY (same chunk
    walk, same pack_hashed jitter over global (pod row, node column)
    coordinates, same merge_topk carry) so the surviving candidates are
    bit-identical to the full pass over an equal mask/score field —
    the byte-identity contract's tail half.  Payload columns come back
    zeroed; ``attach_payload`` gathers them from the live table (the
    values are gated by feasibility downstream, so end-gather equals
    the full pass's per-chunk gather byte-for-byte).
    """
    from k8s1m_tpu.engine.cycle import (
        Candidates,
        chunk_topk,
        empty_candidates,
        merge_topk,
    )
    from k8s1m_tpu.ops.priority import pack_hashed

    n = pmask.shape[1]
    if n % chunk:
        raise ValueError(f"plane rows {n} not divisible by chunk {chunk}")
    num_chunks = n // chunk
    b = slot_ids.shape[0]
    pod_rows = lax.broadcasted_iota(jnp.int32, (b, 1), 0) + pod_offset
    zeros = jnp.zeros((b, k), jnp.int32)

    def body(carry, _):
        carry, ci = carry
        start = ci * chunk
        m = jnp.take(
            lax.dynamic_slice_in_dim(pmask, start, chunk, 1), slot_ids, 0
        )
        sc = jnp.take(
            lax.dynamic_slice_in_dim(pscore, start, chunk, 1), slot_ids, 0
        )
        node_cols = (
            lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
            + start + row_offset
        )
        prio = pack_hashed(sc, seed, m, pod_rows, node_cols, stratum_bits)
        top_prio, idx = chunk_topk(prio, k)
        local = Candidates(
            idx=(idx + start + row_offset).astype(jnp.int32),
            prio=top_prio,
            cpu=zeros, mem=zeros, pods=zeros, zone=zeros, region=zeros,
        )
        return (merge_topk(carry, local, k), ci + 1), None

    init = (empty_candidates(b, k), jnp.int32(0))
    if num_chunks == 1:
        (cand, _), _ = body(init, None)
    else:
        (cand, _), _ = lax.scan(body, init, None, length=num_chunks)
    return cand.replace(idx=jnp.where(cand.prio >= 0, cand.idx, -1))


# ---- score-stratified candidate index (device half) -----------------------
#
# Per resident shape slot, an HBM top-K candidate set over the cached
# plane: ``idx_row i32[S, K]`` (global rows, stored ASCENDING — the
# storage order IS the earlier-row-wins tie-break of the full chunk
# scan), ``idx_class i32[S, K]`` (ops/priority.class_key: the top
# 11 + stratum_bits priority bits, the part independent of seed and pod
# row; -1 = empty entry, whose row holds the out-of-bounds sentinel N),
# and ``idx_floor i32[S]``.  The floor invariant everything rests on:
#
#     every feasible row NOT in a slot's index has class_key <= floor.
#
# floor == -1 means the index is EXHAUSTIVE (never evicted: it holds
# every feasible row); floor == INDEX_FLOOR_UNBUILT means the slot has
# no index yet (fresh fill, reset) and fails closed.  A wave may derive
# its candidates from the index iff every used slot has >= k entries
# STRICTLY above its floor (or is exhaustive): those entries beat every
# unindexed row for every wave seed and every pod row (class_key doc),
# so the true top-k is a subset of the index and the reconstructed
# priorities — (class << low) | per-pod jitter low bits — are
# bit-identical to pack_hashed over the full plane.  Anything else
# fails closed to plane_topk, counted in deltasched_index_*.

INDEX_FLOOR_UNBUILT = np.iinfo(np.int32).max


def dedup_rows(rows, n: int):
    """First-occurrence filter over the combined dirty vector: entries
    whose row repeats earlier collapse to the out-of-bounds sentinel.
    The plane scatter-merge tolerates duplicates (same row recomputes
    the same column), but the index update must not insert one row
    twice — a duplicate entry would shadow a real candidate out of the
    top K and break the floor invariant's counting."""
    d = rows.shape[0]
    iota = lax.iota(jnp.int32, d)
    first = jnp.full(n + 1, d, jnp.int32).at[rows].min(iota)
    keep = (rows < n) & (first[rows] == iota)
    return jnp.where(keep, rows, n)


def _sort_desc_class(cls, row, keep: int):
    """Two-key sort of candidate entries — descending class, ties by
    ASCENDING row (deterministic, and the kept boundary then matches
    the full scan's earlier-row-wins order) — returning the first
    ``keep`` entries re-sorted to ascending-row storage order plus the
    class of the first DISCARDED entry (the eviction-floor raise)."""
    neg, row_s = lax.sort((-cls, row), num_keys=2, dimension=1)
    kept_cls, kept_row = -neg[:, :keep], row_s[:, :keep]
    spill = neg[:, keep] * -1
    kept_row, kept_cls = lax.sort((kept_row, kept_cls), num_keys=1, dimension=1)
    return kept_row, kept_cls, spill


def update_index(
    idx_row, idx_class, idx_floor, rep_idx, rows, mask_d, score_d, n: int,
    *, stratum_bits: int,
):
    """Apply one wave's dirty slice to the candidate index, in-step.

    ``rows`` is the deduped dirty vector (sentinel = ``n``, the plane
    row count); ``mask_d`` / ``score_d`` are merge_dirty_planes'
    recomputed per-pod columns ([B, D]) and ``rep_idx i32[S]`` names
    one batch position per slot USED this wave (sentinel = batch size)
    — any pod of the slot's shape scores identically, so one
    representative row of the recompute is the slot's entire dirty
    view.  Per used slot: invalidate entries whose row went dirty,
    re-insert dirty rows that are feasible and STRICTLY above the
    floor, keep the top K by (class desc, row asc), and raise the
    floor to the best evicted class.  Slots without a representative
    (not used this wave) are untouched — their stale rows stay covered
    by the same freshness-stamp dirty-slice discipline that covers
    their planes."""
    from k8s1m_tpu.ops.priority import class_key

    b = mask_d.shape[0]
    rep = jnp.clip(rep_idx, 0, b - 1)
    valid_rep = rep_idx < b
    m = jnp.take(mask_d, rep, 0)          # [S, D]
    sc = jnp.take(score_d, rep, 0)        # [S, D]

    cls_d = class_key(sc, rows[None, :], stratum_bits)
    qualify = m & (rows < n)[None, :] & (cls_d > idx_floor[:, None])
    cand_cls = jnp.where(qualify, cls_d, -1)
    cand_row = jnp.where(qualify, jnp.broadcast_to(rows[None, :], cls_d.shape), n)

    flag = jnp.zeros((n + 1,), jnp.bool_).at[rows].set(True)
    inv = flag[idx_row]
    old_cls = jnp.where(inv, -1, idx_class)
    old_row = jnp.where(inv, n, idx_row)

    k_idx = idx_row.shape[1]
    merged_cls = jnp.concatenate([old_cls, cand_cls], axis=1)
    merged_row = jnp.concatenate([old_row, cand_row], axis=1)
    new_row, new_cls, spill = _sort_desc_class(merged_cls, merged_row, k_idx)
    new_floor = jnp.maximum(idx_floor, spill)

    vr = valid_rep[:, None]
    return (
        jnp.where(vr, new_row, idx_row),
        jnp.where(vr, new_cls, idx_class),
        jnp.where(valid_rep, new_floor, idx_floor),
    )


def index_usable(idx_class, idx_floor, slot_ids, k: int):
    """Device scalar: may THIS wave derive candidates from the index?
    Per slot: >= k entries strictly above the floor, or exhaustive
    (floor -1, never evicted — then the index IS the feasible set and
    fewer than k entries reproduces the full scan's padding exactly).
    The padding slot (sentinel = slot count) is always usable.  The
    decision stays on device (lax.cond selects the tail), so failing
    closed costs no host sync."""
    above = jnp.sum((idx_class > idx_floor[:, None]).astype(jnp.int32), axis=1)
    ok = (above >= k) | (idx_floor == -1)
    ok = jnp.concatenate([ok, jnp.ones((1,), jnp.bool_)])
    return jnp.all(ok[slot_ids])


def index_topk(
    idx_row, idx_class, slot_ids, seed, *, k: int, stratum_bits: int,
):
    """plane_topk's sublinear twin: per-pod hashed top-k over the K
    index entries instead of the N plane columns.  Priorities
    reconstruct as (class << low) | (per-pod jitter & low-mask) — by
    the class_key decomposition this is bit-identical to pack_hashed
    over the same (seed, pod row, node column), and the ascending-row
    storage order makes chunk_topk's earlier-index-wins tie rule
    coincide with the full scan's earlier-row-wins.  Single-device
    only: the index is not maintained under a mesh (the sharded delta
    step always runs the plane tail)."""
    from k8s1m_tpu.engine.cycle import Candidates, chunk_topk
    from k8s1m_tpu.ops.priority import JITTER_BITS, hash_jitter

    b = slot_ids.shape[0]
    s = idx_row.shape[0]
    sl = jnp.clip(slot_ids, 0, s - 1)  # padding pods read slot S-1, like jnp.take
    rows = idx_row[sl]                 # [B, K] global rows (sentinel = N)
    cls = idx_class[sl]
    pod_rows = lax.broadcasted_iota(jnp.int32, (b, 1), 0)
    low = JITTER_BITS - stratum_bits
    j = hash_jitter(seed, pod_rows, rows)
    prio = jnp.where(cls >= 0, (cls << low) | (j & ((1 << low) - 1)), -1)
    top_prio, sel = chunk_topk(prio, k)
    idx = jnp.take_along_axis(rows, sel, axis=1)
    zeros = jnp.zeros((b, k), jnp.int32)
    cand = Candidates(
        idx=idx.astype(jnp.int32), prio=top_prio,
        cpu=zeros, mem=zeros, pods=zeros, zone=zeros, region=zeros,
    )
    return cand.replace(idx=jnp.where(cand.prio >= 0, cand.idx, -1))


def rebuild_index(
    pmask, pscore, rebuild_slots, rep_idx, idx_row, idx_class, idx_floor,
    *, chunk: int, stratum_bits: int, batch_b: int,
):
    """The plane tail's index maintenance: rebuild the candidate index
    from the merged planes for the (host-rotated, fill_batch-bounded)
    ``rebuild_slots``, and fail every OTHER slot used this wave closed
    (floor = INDEX_FLOOR_UNBUILT).  The wave's freshness stamps advance
    for all used slots at commit, so a used slot that neither rebuilt
    nor invalidated would hold entries the dirty-slice discipline will
    never revisit — a silent byte-identity break.  Chunked running
    top-K: per chunk, class the feasible columns, two-key sort against
    the carry, track the best discarded class as the floor."""
    from k8s1m_tpu.ops.priority import class_key

    s, n = pmask.shape
    k_idx = idx_row.shape[1]
    r = rebuild_slots.shape[0]
    rs = jnp.clip(rebuild_slots, 0, s - 1)
    num_chunks = n // chunk

    def body(carry, _):
        crow, ccls, cfloor, ci = carry
        start = ci * chunk
        pm = jnp.take(lax.dynamic_slice_in_dim(pmask, start, chunk, 1), rs, 0)
        sc = jnp.take(lax.dynamic_slice_in_dim(pscore, start, chunk, 1), rs, 0)
        cols = lax.broadcasted_iota(jnp.int32, (1, chunk), 1) + start
        cls = jnp.where(pm, class_key(sc, cols, stratum_bits), -1)
        rows = jnp.where(pm, cols + jnp.zeros((r, 1), jnp.int32), n)
        mrow = jnp.concatenate([crow, rows], axis=1)
        mcls = jnp.concatenate([ccls, cls], axis=1)
        nrow, ncls, spill = _sort_desc_class(mcls, mrow, k_idx)
        return (nrow, ncls, jnp.maximum(cfloor, spill), ci + 1), None

    init = (
        jnp.full((r, k_idx), n, jnp.int32),
        jnp.full((r, k_idx), -1, jnp.int32),
        jnp.full((r,), -1, jnp.int32),
        jnp.int32(0),
    )
    if num_chunks == 1:
        (crow, ccls, cfloor, _), _ = body(init, None)
    else:
        (crow, ccls, cfloor, _), _ = lax.scan(body, init, None, length=num_chunks)

    # Used-but-not-rebuilt slots fail closed; rebuilt slots scatter in
    # (the padding sentinel in rebuild_slots drops out of range).
    used = rep_idx < batch_b
    rebuilt = jnp.zeros((s + 1,), jnp.bool_).at[rebuild_slots].set(True)[:s]
    idx_floor = jnp.where(used & ~rebuilt, INDEX_FLOOR_UNBUILT, idx_floor)
    idx_row = idx_row.at[rebuild_slots].set(crow, mode="drop")
    idx_class = idx_class.at[rebuild_slots].set(ccls, mode="drop")
    idx_floor = idx_floor.at[rebuild_slots].set(cfloor, mode="drop")
    return idx_row, idx_class, idx_floor


def note_index_oversized() -> None:
    """Host stamp at launch for an index-enabled wave whose dirty slice
    exceeded index_dirty_cap: the step compiled the plane-only variant,
    so the in-step index update never ran (trace-time shape decision,
    engine/cycle._jitted_schedule_delta)."""
    _INDEX_DROPS.inc(reason="oversized-dirty")


def note_index_wave(
    flag: int, attempted: bool, touched_index: int, touched_plane: int
) -> None:
    """Host stamp at wave retire for one index-enabled delta wave:
    ``flag`` is the device path flag the step returned (1 = candidates
    came from the index, 0 = plane tail), ``attempted`` the host-side
    dirty-cap decision, and the touched counts feed the sublinearity
    ratio.  An attempted wave that still ran the plane tail is an
    eviction-floor underflow — the fail-closed path the index metric
    family exists to make visible."""
    if flag:
        _INDEX_WAVES.inc(path="index")
        _INDEX_TOUCHED.inc(touched_index, path="index")
    else:
        _INDEX_WAVES.inc(path="plane")
        _INDEX_TOUCHED.inc(touched_plane, path="plane")
        if attempted:
            _INDEX_DROPS.inc(reason="underflow")


def attach_payload(table, cand, row_offset=0):
    """Gather the candidate payload (free capacity at batch start,
    topology domains) from the live table at the surviving top-k rows.

    The full pass gathers these per chunk during the scan; the table
    does not change within a step, so gathering at the end reads the
    identical values — and infeasible candidates' payload (clipped
    garbage) is unread downstream (greedy_assign gates on prio >= 0,
    the assignment gates on bound)."""
    local = cand.idx - row_offset
    sub = gather_rows(table, local.reshape(-1))
    free_cpu, free_mem, free_pods = sub.free()
    shape = cand.idx.shape
    return cand.replace(
        cpu=free_cpu.reshape(shape),
        mem=free_mem.reshape(shape),
        pods=free_pods.reshape(shape),
        zone=sub.zone.reshape(shape),
        region=sub.region.reshape(shape),
    )


def fill_planes_scan(
    table, batch, profile: Profile, fill_slots, pmask, pscore, *, chunk: int
):
    """Populate plane rows for a batch of shape representatives: one
    full chunked filter+score pass over the (shard-local) table, each
    chunk's columns scattered into the representatives' slots.  The
    sentinel slot (out of bounds) drops padded representatives."""
    from k8s1m_tpu.engine.cycle import _slice_table

    n = pmask.shape[1]
    if n % chunk:
        raise ValueError(f"plane rows {n} not divisible by chunk {chunk}")
    num_chunks = n // chunk

    def body(carry, _):
        pmask, pscore, ci = carry
        start = ci * chunk
        tchunk = _slice_table(table, start, chunk)
        mask, score = score_and_filter(tchunk, batch, profile, None, None)
        cols = start + lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        at = (fill_slots[:, None], cols)
        pmask = pmask.at[at].set(mask, mode="drop")
        pscore = pscore.at[at].set(score, mode="drop")
        return (pmask, pscore, ci + 1), None

    init = (pmask, pscore, jnp.int32(0))
    if num_chunks == 1:
        (pmask, pscore, _), _ = body(init, None)
    else:
        (pmask, pscore, _), _ = lax.scan(body, init, None, length=num_chunks)
    return pmask, pscore


# ---- host-side cache controller -------------------------------------------


@dataclasses.dataclass
class WavePlan:
    """One wave's delta decision (DeltaPlaneCache.plan).

    ``fill_idx``/``fill_slots`` name the batch positions whose shapes
    must be plane-filled BEFORE the wave dispatches (recurring shapes
    being promoted, or stale slots being refreshed) — the coordinator
    encodes those representatives and runs the fill executable whether
    or not the wave itself goes delta.  ``slot_ids`` is None for a full
    wave (some shape stayed unresolvable); otherwise the wave runs the
    delta step with ``dirty`` (sentinel-padded global rows) and the
    stamps in ``stamp_slots`` applied at commit time."""

    fill_idx: list[int]
    fill_slots: list[int]
    slot_ids: np.ndarray | None = None
    dirty: np.ndarray | None = None
    stamp_slots: tuple[int, ...] = ()
    stamp_ver: int = 0
    # Candidate-index plumbing (index_k > 0 caches only): one
    # representative batch position per slot (sentinel = batch size)
    # for the in-step index update, and the fill_batch-bounded,
    # host-rotated slot list the plane tail rebuilds when the index
    # fails closed.  None when the cache runs without an index.
    rep_idx: np.ndarray | None = None
    rebuild_slots: np.ndarray | None = None


class DeltaPlaneCache:
    """Host controller of the HBM-resident per-shape plane cache.

    Owns the device plane buffers (``bool[S, N]`` mask + ``i32[S, N]``
    score, sharded over ``sp`` on the row axis under a mesh), the shape
    key → slot map with LRU eviction, the per-slot freshness stamps,
    and the row-version journal consumers invalidate through.  All
    state is cycle-thread-confined, like the dirty-row sets it mirrors.
    """

    def __init__(
        self,
        num_rows: int,
        *,
        slots: int = 64,
        fill_batch: int = 16,
        journal_cap: int | None = None,
        seen_cap: int = 1 << 16,
        dirty_cap: int | None = None,
        sharding=None,
        index_k: int = 0,
        stratum_bits: int = 0,
        index_dirty_cap: int | None = None,
    ) -> None:
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if index_k < 0:
            raise ValueError(f"index_k must be >= 0, got {index_k}")
        if index_k and sharding is not None:
            # The index is a single-device structure: under a mesh the
            # delta wave always runs the (shard-local) plane tail, and a
            # silently-ignored index flag would report index-path waves
            # that never happened.
            raise ValueError(
                "the candidate index does not compose with sharded "
                "planes; run index_k=0 under a mesh"
            )
        self.num_rows = num_rows
        self.slots = slots
        self.fill_batch = fill_batch
        # Score-stratified candidate index (index_k > 0): per-slot
        # top-index_k candidate set over the cached plane, letting an
        # all-hit wave skip the O(batch x N) plane scan.  stratum_bits
        # must match the coordinator's (every pack_hashed call in the
        # system must draw the same jitter, or the index's class
        # algebra diverges from the real priorities).
        self.index_k = index_k
        self.stratum_bits = stratum_bits
        # Past this many combined dirty rows the in-step [S, K+D] sort
        # stops being a bargain; the wave takes the plane tail (and its
        # chunked rebuild) instead.  Trace-static: the dirty vector is
        # power-of-two padded, so this is a shape cutoff, not a value.
        self.index_dirty_cap = (
            index_dirty_cap if index_dirty_cap is not None
            else max(index_k, 1 << 12)
        )
        self._rebuild_rot = 0
        # Past this many dirty rows the delta recompute stops being a
        # bargain; the plan refreshes the used slots wholesale instead
        # (a fill is one F-pod pass, far cheaper than a B-pod full wave)
        # and the wave still runs delta over an empty dirty set.
        self.dirty_cap = (
            dirty_cap if dirty_cap is not None else max(num_rows // 4, 1)
        )
        if journal_cap is None:
            # Scale-aware journal bound (ISSUE 14): the cap tracks the
            # TABLE SIZE, not a fixed row budget — at the old 1<<16 a
            # 1M-row churn burst compacted the journal every wave and
            # fail-closed the whole cache to wholesale refills.  Half
            # the table (compacting down to dirty_cap, a quarter) keeps
            # the enumerable window a constant FRACTION of rows: the
            # delta lane stays plannable right up to the dirty_cap
            # break-even it would abandon anyway.  At 131072 rows this
            # derives exactly the old 1<<16 — the fixed-cap
            # differential gate (tests/test_megarow.py).
            journal_cap = max(1 << 16, num_rows // 2)
        self.versions = RowVersions(cap=journal_cap)
        self._sharding = sharding
        self._mask = None           # bool[S, N] device plane
        self._score = None          # i32[S, N] device plane
        self._idx_row = None        # i32[S, K] candidate rows (ascending)
        self._idx_class = None      # i32[S, K] candidate class keys
        self._idx_floor = None      # i32[S] eviction floors
        self._slot_of: collections.OrderedDict = collections.OrderedDict()
        self._free: list[int] = list(range(slots - 1, -1, -1))
        self._fresh: dict[int, int] = {}     # slot -> version stamp
        self._gen = -1                       # vocab generation of planes
        # Shapes seen once before (promotion gate: a shape plane-fills
        # only on its SECOND sighting, so one-shot shapes — the cold/
        # high-churn lane — never pay a fill).  Bounded like the
        # coordinator's _gang_oversize set: clearing just re-requires
        # one extra sighting from a repeat shape.
        self._seen: set = set()
        self._seen_cap = seen_cap
        _LIVE_CACHES.add(self)

    # -- device buffers ---------------------------------------------------

    def ensure_device(self) -> None:
        if self._mask is not None:
            return
        s, n = self.slots, self.num_rows
        mask = jnp.zeros((s, n), jnp.bool_)
        score = jnp.zeros((s, n), jnp.int32)
        if self._sharding is not None:
            mask = jax.device_put(mask, self._sharding)
            score = jax.device_put(score, self._sharding)
        self._mask, self._score = mask, score
        if self.index_k:
            # Fresh index buffers fail closed by construction: every
            # floor starts at the unbuilt sentinel, so no slot is
            # usable until the plane tail rebuilds it.
            self._idx_row = jnp.full((s, self.index_k), n, jnp.int32)
            self._idx_class = jnp.full((s, self.index_k), -1, jnp.int32)
            self._idx_floor = jnp.full((s,), INDEX_FLOOR_UNBUILT, jnp.int32)

    def planes(self, gen: int):
        """THE epoch-checked plane accessor (deltacache-epoch-keyed
        lint contract): hands out the device buffers only against the
        generation they were computed at.  A mismatch is a caller bug —
        the cache must be generation-checked (check_generation) before
        any wave planning touches it."""
        if gen != self._gen:
            raise RuntimeError(
                f"delta plane access at generation {gen} but planes are "
                f"stamped {self._gen}; call check_generation first"
            )
        self.ensure_device()
        return self._mask, self._score

    def index_state(self, gen: int):
        """The candidate-index twin of ``planes``: the epoch-checked
        accessor for the (idx_row, idx_class, idx_floor) device buffers
        (deltacache-index-keyed lint contract — raw attribute reads
        outside this module would let a stale-generation index reach a
        wave)."""
        if not self.index_k:
            raise RuntimeError("index_state on a cache built with index_k=0")
        if gen != self._gen:
            raise RuntimeError(
                f"candidate-index access at generation {gen} but planes "
                f"are stamped {self._gen}; call check_generation first"
            )
        self.ensure_device()
        return self._idx_row, self._idx_class, self._idx_floor

    def commit(self, mask, score, plan: WavePlan | None = None,
               index=None) -> None:
        """Store the (donated-through) plane buffers back and apply the
        plan's freshness stamps — called only after the dispatch that
        consumed the old buffers succeeded.  ``index`` is the donated-
        through (idx_row, idx_class, idx_floor) triple for index-enabled
        caches (the index shares the planes' freshness stamps: both are
        updated together for every used slot, in both tails)."""
        self._mask, self._score = mask, score
        if index is not None:
            self._idx_row, self._idx_class, self._idx_floor = index
        if plan is not None:
            for s in plan.stamp_slots:
                self._fresh[s] = plan.stamp_ver

    # -- invalidation -----------------------------------------------------

    def note_rows(self, rows) -> None:
        """Journal one batch of device-table row mutations (called when
        the mutating scatter/commit is DISPATCHED, so stream order
        guarantees later delta waves recompute from the new values)."""
        if self._slot_of or self._seen:
            self.versions.note(rows)

    def check_generation(self, gen: int) -> None:
        """Drop everything when the vocab generation moved: cached
        planes bake interned ids (tolerated taint sets, selector value
        ids), and a new id can change what an identical shape encodes."""
        if gen != self._gen:
            if self._slot_of:
                self.drop_all("generation")
            self._gen = gen

    def drop_all(self, reason: str) -> None:
        """Wholesale invalidation: table rebuilds (packing widening,
        mesh/device re-upload), resync, vocab generation movement.  The
        device buffers stay allocated — only the host keying drops, so
        the next fills simply overwrite."""
        if self._slot_of:
            log.info(
                "deltasched: dropping %d cached shape planes (%s)",
                len(self._slot_of), reason,
            )
        self._free = list(range(self.slots - 1, -1, -1))
        self._slot_of.clear()
        self._fresh.clear()
        self._seen.clear()
        if self.index_k:
            # The candidate index dies with the keying: a dropped slot
            # can only come back through a fill, and note_fill stamps
            # its floor to the unbuilt sentinel before any wave reads
            # it — so no device work is needed here, just the count.
            _INDEX_DROPS.inc(reason=reason)
        # Everything before this point is unenumerable by construction.
        self.versions.release(self.versions.ver + 1)

    def reset(self, reason: str) -> None:
        """drop_all PLUS discard the device buffers (a failed donating
        dispatch leaves them in an unknown consumed state); the next
        ensure_device reallocates zeros."""
        self.drop_all(reason)
        self._mask = self._score = None
        self._idx_row = self._idx_class = self._idx_floor = None

    # -- wave planning ----------------------------------------------------

    def _note_seen(self, key) -> None:
        if len(self._seen) >= self._seen_cap:
            self._seen.clear()
        self._seen.add(key)

    def _alloc_slot(self, key, busy) -> int | None:
        """A slot for ``key``: a free one, else LRU-evict — but NEVER a
        slot in ``busy`` (already assigned to a pod of the CURRENT
        wave): evicting one would refill it with this key's plane and
        the earlier pod would silently read the wrong shape's mask/
        score — a byte-identity break with no error.  Returns None when
        every resident slot is busy (the wave takes the full pass)."""
        if self._free:
            slot = self._free.pop()
        else:
            victim = next(
                (
                    (k, s) for k, s in self._slot_of.items()  # LRU first
                    if s not in busy
                ),
                None,
            )
            if victim is None:
                return None
            del self._slot_of[victim[0]]
            slot = victim[1]
            self._fresh.pop(slot, None)
            _EVICTIONS.inc()
        self._slot_of[key] = slot
        return slot

    def plan(self, keys, batch_b: int) -> WavePlan:
        """Decide this wave's path from the pods' shape keys.

        ``keys`` is one entry per real pod (None = uncacheable shape);
        ``batch_b`` is the encoded batch size (padding gets the slot
        sentinel).  Returns a WavePlan: fills to dispatch first, and —
        when every shape resolved to a live slot — the delta step's
        slot ids, sentinel-padded dirty rows, and commit stamps.
        """
        fills_idx: list[int] = []
        fills_slot: list[int] = []
        if any(k is None for k in keys):
            # Constraint-coupled shapes poison the whole wave (their
            # pods need the real constraint stage); no fills either —
            # mixed waves are the cold lane, keep it zero-overhead.
            _SHAPE_MISSES.inc(len(keys))
            _WAVES.inc(path="full")
            return WavePlan([], [])
        slot_ids = np.full(batch_b, self.slots, np.int32)
        hits = misses = 0
        missing = False
        filled_keys: dict = {}
        busy: set[int] = set()   # slots assigned to THIS wave so far
        for i, key in enumerate(keys):
            slot = self._slot_of.get(key)
            if slot is not None:
                self._slot_of.move_to_end(key)
                slot_ids[i] = slot
                busy.add(slot)
                hits += 1
                continue
            misses += 1
            prior = filled_keys.get(key)
            if prior is not None:
                slot_ids[i] = prior
                continue
            if key in self._seen and len(fills_idx) < self.fill_batch:
                slot = self._alloc_slot(key, busy)
                if slot is None:
                    # Every resident slot belongs to a pod of this very
                    # wave: no evictable victim.  Full pass.
                    missing = True
                    continue
                fills_idx.append(i)
                fills_slot.append(slot)
                filled_keys[key] = slot
                slot_ids[i] = slot
                busy.add(slot)
            else:
                self._note_seen(key)
                missing = True
        _SHAPE_HITS.inc(hits)
        if misses:
            _SHAPE_MISSES.inc(misses)
        if missing:
            _WAVES.inc(path="full")
            return WavePlan(fills_idx, fills_slot)

        # Dirty slice: rows mutated since the stalest used slot's fill.
        used = sorted({int(s) for s in slot_ids if s < self.slots})
        fresh_fills = set(fills_slot)
        stale = [
            s for s in used
            if s not in fresh_fills
            and self._fresh.get(s, -1) < self.versions.floor
        ]
        dirty: set[int] | None = set()
        live = [s for s in used if s not in fresh_fills and s not in stale]
        if live:
            vmin = min(self._fresh[s] for s in live)
            dirty = self.versions.rows_since(vmin)
        if dirty is None or len(dirty) > self.dirty_cap or stale:
            # Unenumerable or oversized delta (journal compaction, a
            # churn burst): refresh every used slot wholesale — one
            # F-shape fill pass — and run delta over the in-flight rows
            # alone.  Slots past the fill budget force the full pass.
            refresh = [s for s in used if s not in fresh_fills]
            if len(fills_idx) + len(refresh) > self.fill_batch:
                _WAVES.inc(path="full")
                return WavePlan(fills_idx, fills_slot)
            slot_at = {int(s): i for i, s in enumerate(slot_ids) if s < self.slots}
            for s in refresh:
                fills_idx.append(slot_at[s])
                fills_slot.append(s)
            dirty = set()
        _WAVES.inc(path="delta")
        _DIRTY_ROWS.inc(len(dirty))
        rep_idx = rebuild = None
        if self.index_k:
            rep_idx = np.full(self.slots, batch_b, np.int32)
            for i, s in enumerate(slot_ids.tolist()):
                if s < self.slots and rep_idx[s] == batch_b:
                    rep_idx[s] = i
            # Plane-tail rebuild list: fresh fills first (their floors
            # just failed closed), then the other used slots rotated so
            # a wave using more than fill_batch slots still converges
            # over consecutive underflow waves instead of starving a
            # fixed suffix.
            others = [s for s in used if s not in fresh_fills]
            if others:
                r = self._rebuild_rot % len(others)
                self._rebuild_rot += 1
                others = others[r:] + others[:r]
            order = list(fills_slot) + others
            rebuild = np.full(self.fill_batch, self.slots, np.int32)
            take = order[: self.fill_batch]
            rebuild[: len(take)] = take
        return WavePlan(
            fills_idx, fills_slot,
            slot_ids=slot_ids,
            dirty=self._pad_dirty(dirty),
            stamp_slots=tuple(used),
            stamp_ver=self.versions.ver,
            rep_idx=rep_idx,
            rebuild_slots=rebuild,
        )

    def _pad_dirty(self, rows: set) -> np.ndarray:
        """Sorted, power-of-two-padded dirty rows with the out-of-bounds
        sentinel (= num_rows) as padding, so the jitted step sees a
        handful of shapes instead of one trace per dirty count."""
        arr = np.fromiter(rows, np.int32, len(rows))
        arr.sort()
        cap = 1 << max(0, int(max(arr.size, 1) - 1).bit_length())
        out = np.full(cap, self.num_rows, np.int32)
        out[: arr.size] = arr
        return out

    def note_fill(self, plan: WavePlan) -> None:
        """Stamp freshly-filled slots at the journal version their fill
        dispatch observed (called right after the fill executable is
        enqueued)."""
        _FILLS.inc(len(plan.fill_slots))
        if self.index_k and plan.fill_slots:
            # A refilled slot's plane is brand new; its candidate index
            # is not.  Fail it closed (unbuilt floor) so the first wave
            # that uses it takes the plane tail and rebuilds — one tiny
            # host-dispatched scatter, ordered before the wave on the
            # same stream.
            self.ensure_device()
            self._idx_floor = self._idx_floor.at[
                np.asarray(plan.fill_slots, np.int32)
            ].set(INDEX_FLOOR_UNBUILT)
            _INDEX_DROPS.inc(len(plan.fill_slots), reason="fill")
        for s in plan.fill_slots:
            self._fresh[s] = self.versions.ver

    def abort_fills(self, plan: WavePlan) -> None:
        """Un-allocate the plan's fill slots (the representative encode
        failed, e.g. a query-key overflow across fill shapes): the keys
        drop back to seen-once and the wave takes the full pass."""
        for s in plan.fill_slots:
            self._fresh.pop(s, None)
            self._free.append(s)
        for key, slot in list(self._slot_of.items()):
            if slot in set(plan.fill_slots):
                del self._slot_of[key]
        plan.fill_idx.clear()
        plan.fill_slots.clear()

    @property
    def resident(self) -> int:
        return len(self._slot_of)
