"""faultline: deterministic fault injection + unified retry/backoff.

The reference survives week-long watch sessions, an 11-replica apiserver
tier, and kubelet churn at 1M nodes (reference README.adoc:410-416,
server.tf:230-251) — but every recovery behavior this repro claims
(watch re-attach, CAS-bind conflict handling, dead-shard evacuation,
tier-replica failover) used to be exercised by one bespoke kill drill
each, with retries and timeouts hand-rolled per call site.  This package
replaces both halves with a reusable subsystem:

- **Injection** (`plan.py`): a seeded, deterministic ``FaultPlan`` —
  drop / delay / disconnect / err5xx / partial-write / stale-revision
  / stall / slow-cycle faults keyed by component x operation, fired by
  probability or schedule — with hooks threaded into the store wire
  client (store/remote.py), the watch-cache event pump
  (store/watch_cache.py), the coordinator's bind/CAS, watch-drain and
  cycle-dispatch paths (control/coordinator.py; the overload-shaped
  ``stall`` / ``slow_cycle`` kinds drive the loadshed breaker and
  latency signals), and the shardset lease/rebalance loop
  (control/shardset.py).  Enabled via ``ClusterSpec(fault_plan=...)``,
  a ``--fault-plan JSON`` flag on sched_bench / store_stress / soak, or
  the ``K8S1M_FAULT_PLAN`` env var (how subprocess topologies inherit
  the plan).  Same seed => same injected-fault sequence, asserted in
  tests/test_faultline.py.

- **Resilience** (`policy.py`): one ``RetryPolicy`` (capped exponential
  backoff + jitter + deadline budget) with per-component defaults,
  replacing the scattered hand-rolled loops.  Give-up degrades
  gracefully rather than erroring out: a broken watch falls back to
  relist-from-last-revision (the consumer resync contract), the
  coordinator requeues conflicted pods with backoff (conflict storms
  become backpressure, not a tight loop), and the shardset masks a
  silent shard dead and evacuates its groups.

Metrics: ``faultline_injected_total{component,kind}``,
``retry_attempts_total{component}``, ``retry_give_ups_total{component}``.
"""

from k8s1m_tpu.faultline.plan import (
    FAULT_KINDS,
    NAMED_PLANS,
    FaultDecision,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    Injector,
    acheck,
    active_injector,
    check,
    decide,
    install_plan,
)
from k8s1m_tpu.faultline.policy import (
    DEFAULT_POLICIES,
    GiveUp,
    RetryPolicy,
    give_up_counts,
    note_give_up,
    note_recovery,
    note_retry,
    policy_for,
    recovery_stats,
    retry_counts,
)

__all__ = [
    "FAULT_KINDS",
    "NAMED_PLANS",
    "acheck",
    "FaultDecision",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "Injector",
    "active_injector",
    "check",
    "decide",
    "install_plan",
    "DEFAULT_POLICIES",
    "GiveUp",
    "RetryPolicy",
    "give_up_counts",
    "note_give_up",
    "note_recovery",
    "note_retry",
    "policy_for",
    "recovery_stats",
    "retry_counts",
]
