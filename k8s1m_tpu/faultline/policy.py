"""The one retry/backoff policy every component shares.

Before this module, failure handling was hand-rolled per call site:
``control/coordinator.py`` counted flat attempts with no backoff (a
conflict storm re-entered the very next wave), ``tools/common.py`` ran a
bare ``for attempt in range(retries+1)`` with zero sleep, and
``store/watch_cache.py`` relisted on a fixed 200ms nap.  ``RetryPolicy``
replaces all of them: capped exponential backoff with full jitter
(the AWS-style decorrelated-sleep shape that keeps retry waves from
synchronizing into thundering herds) under a total deadline budget, with
per-component defaults in ``DEFAULT_POLICIES``.

Give-up is a *policy edge*, not an error path: ``call`` raises
``GiveUp`` carrying the last error, and each component maps that to its
graceful degradation — the watch consumer relists from its last resume
revision, the coordinator parks the pod as unschedulable after bounded
requeues, the shardset lets the rebalancer evacuate a shard that cannot
heartbeat.

Metrics: ``retry_attempts_total{component}`` (every retry, i.e. attempts
beyond the first), ``retry_give_ups_total{component}``.
Each successful call that needed retries also records a *recovery
sample* — wall time from the first failure to the eventual success —
keyed by fault class (the injected kind when the first error was an
``InjectedFault``, else the component name).  ``recovery_stats()``
reduces the samples to count/p50/p99 per class: the soak's
"p99 recovery time per fault class" evidence.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

from k8s1m_tpu.faultline.plan import InjectedFault
from k8s1m_tpu.obs.metrics import Counter

_RETRIES = Counter(
    "retry_attempts_total",
    "Retry attempts (beyond the first try), by component",
    ("component",),
)
_GIVEUPS = Counter(
    "retry_give_ups_total",
    "Operations abandoned after exhausting the retry budget",
    ("component",),
)

# Recovery samples: first-failure -> eventual-success wall time, by
# fault class.  Bounded per class so a week-long soak cannot grow this
# without limit (the tail quantiles stabilize long before the cap).
_REC_CAP = 65536
_REC_LOCK = threading.Lock()
_recovery: dict[str, list[float]] = {}


def _fault_class(e: Exception, component: str) -> str:
    return e.decision.kind if isinstance(e, InjectedFault) else component


def note_recovery(fault_class: str, seconds: float) -> None:
    """Record one recovered-after-failure duration (also called by the
    soak driver for process-level classes like ``tier_kill``)."""
    with _REC_LOCK:
        samples = _recovery.setdefault(fault_class, [])
        if len(samples) < _REC_CAP:
            samples.append(seconds)


def recovery_stats() -> dict[str, dict]:
    """count / p50 / p99 / max seconds per fault class so far."""
    out: dict[str, dict] = {}
    with _REC_LOCK:
        for cls, samples in _recovery.items():
            if not samples:
                continue
            s = sorted(samples)
            out[cls] = {
                "count": len(s),
                "p50_s": round(s[len(s) // 2], 4),
                "p99_s": round(s[min(len(s) - 1, int(len(s) * 0.99))], 4),
                "max_s": round(s[-1], 4),
            }
    return out


class GiveUp(Exception):
    """Retry budget exhausted; ``cause`` is the last underlying error."""

    def __init__(self, component: str, op: str, attempts: int, cause: Exception):
        super().__init__(
            f"{component}/{op}: gave up after {attempts} attempt(s): {cause!r}"
        )
        self.component = component
        self.op = op
        self.attempts = attempts
        self.cause = cause


def default_retryable(e: Exception) -> bool:
    """Transient-wire-error test shared by the store-facing components:
    injected faults and gRPC UNAVAILABLE / DEADLINE_EXCEEDED /
    RESOURCE_EXHAUSTED / connection resets.  Semantic errors
    (CompactedError, CAS conflicts, bad requests) are never retried
    here — they have their own recovery contracts (relist, requeue)."""
    if isinstance(e, InjectedFault):
        return True
    if isinstance(e, (ConnectionError, TimeoutError)):
        return True
    try:
        import grpc
    # Import guard: without grpc there is nothing gRPC-retryable.
    except Exception:  # graftlint: disable=broad-except  # pragma: no cover - grpc is always present in-tree
        return False
    if isinstance(e, grpc.RpcError):
        code = e.code() if callable(getattr(e, "code", None)) else None
        return code in (
            grpc.StatusCode.UNAVAILABLE,
            grpc.StatusCode.DEADLINE_EXCEEDED,
            grpc.StatusCode.RESOURCE_EXHAUSTED,
        )
    return False


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff + full jitter + deadline budget.

    ``max_attempts`` counts tries, not retries (1 = never retry).
    ``deadline_s`` bounds the SUM of sleeps, so a component's worst-case
    stall is explicit instead of emergent from per-site constants."""

    component: str = ""
    max_attempts: int = 5
    base_delay_s: float = 0.02
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5            # fraction of each delay randomized
    deadline_s: float = 30.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")

    def delay_for(self, attempt: int, rng: random.Random | None = None) -> float:
        """Sleep before try ``attempt+1`` (attempt is 1-based: the delay
        after the attempt-th failure).  Deterministic when ``rng`` is
        supplied — the coordinator's backoff requeue threads a seeded rng
        through so a replayed fault plan replays the same schedule."""
        # Exponent capped: retry-forever components (watch.tier) feed an
        # unbounded attempt count through here, and 2.0 ** ~1024 raises
        # OverflowError — the cap is far past where max_delay_s wins.
        d = min(
            self.max_delay_s,
            self.base_delay_s * (self.multiplier ** min(max(0, attempt - 1), 64)),
        )
        if self.jitter:
            r = (rng or random).random()
            d *= (1.0 - self.jitter) + self.jitter * r
        return d

    def _sleeps(self, rng: random.Random | None = None):
        """The bounded sleep schedule: one entry per allowed RETRY."""
        budget = self.deadline_s
        for attempt in range(1, self.max_attempts):
            d = min(self.delay_for(attempt, rng), budget)
            budget -= d
            yield d
            if budget <= 0:
                return

    def call(
        self,
        fn,
        *,
        op: str = "",
        retryable=default_retryable,
        rng: random.Random | None = None,
        sleep=time.sleep,
    ):
        """Run ``fn()`` under this policy; raises GiveUp when the budget
        is exhausted (non-retryable errors propagate immediately)."""
        attempts = 0
        sleeps = self._sleeps(rng)
        first_fail: tuple[float, str] | None = None
        while True:
            attempts += 1
            try:
                result = fn()
            except Exception as e:
                if not retryable(e):
                    raise
                if first_fail is None:
                    first_fail = (
                        time.monotonic(), _fault_class(e, self.component)
                    )
                try:
                    d = next(sleeps)
                except StopIteration:
                    _GIVEUPS.inc(component=self.component)
                    raise GiveUp(self.component, op, attempts, e) from e
                _RETRIES.inc(component=self.component)
                sleep(d)
            else:
                if first_fail is not None:
                    note_recovery(
                        first_fail[1], time.monotonic() - first_fail[0]
                    )
                return result

    async def acall(
        self,
        fn,
        *,
        op: str = "",
        retryable=default_retryable,
        rng: random.Random | None = None,
    ):
        """``call`` for coroutine ``fn`` (asyncio sleeps between tries)."""
        import asyncio

        attempts = 0
        sleeps = self._sleeps(rng)
        first_fail: tuple[float, str] | None = None
        while True:
            attempts += 1
            try:
                result = await fn()
            except Exception as e:
                if not retryable(e):
                    raise
                if first_fail is None:
                    first_fail = (
                        time.monotonic(), _fault_class(e, self.component)
                    )
                try:
                    d = next(sleeps)
                except StopIteration:
                    _GIVEUPS.inc(component=self.component)
                    raise GiveUp(self.component, op, attempts, e) from e
                _RETRIES.inc(component=self.component)
                await asyncio.sleep(d)
            else:
                if first_fail is not None:
                    note_recovery(
                        first_fail[1], time.monotonic() - first_fail[0]
                    )
                return result


# Per-component defaults.  Tuning rationale:
# - store.wire: RPCs on the scheduling hot path; short base so a blip
#   costs ms, capped deadline so a dead store surfaces within ~10s.
# - watch.tier / consumer resync loops: relist is expensive — back off
#   harder, effectively retry forever (the tier's job is to outlive
#   outages; GiveUp would mean abandoning the cache).
# - coordinator.bind: attempts-as-requeues with backoff; matches the
#   historical max_attempts=5 so scheduling-outcome tests keep passing.
# - shardset.lease: a couple of quick tries per tick; the real recovery
#   is the rebalancer's dead-shard evacuation, so give up fast.
# - tools.loadgen: the old run_sharded retried twice flat; keep 3 tries
#   but with jittered backoff so a stressed store is not hammered.
DEFAULT_POLICIES: dict[str, RetryPolicy] = {
    "store.wire": RetryPolicy(
        "store.wire", max_attempts=5, base_delay_s=0.02, max_delay_s=1.0,
        deadline_s=10.0,
    ),
    "watch.tier": RetryPolicy(
        "watch.tier", max_attempts=1_000_000, base_delay_s=0.05,
        max_delay_s=5.0, deadline_s=float("inf"),
    ),
    # The tier's RESUME relist (watch_cache.run_upstream once primed):
    # same retry-forever posture, but a tighter base/cap — a resume
    # races client-visible delivery lag (the watchstorm p99 gate), not
    # bootstrap, and the clients are all still attached and waiting.
    "watch.resume": RetryPolicy(
        "watch.resume", max_attempts=1_000_000, base_delay_s=0.02,
        max_delay_s=1.0, deadline_s=float("inf"),
    ),
    "coordinator.bind": RetryPolicy(
        "coordinator.bind", max_attempts=5, base_delay_s=0.01,
        max_delay_s=0.5, deadline_s=30.0,
    ),
    "shardset.lease": RetryPolicy(
        "shardset.lease", max_attempts=3, base_delay_s=0.01, max_delay_s=0.2,
        deadline_s=2.0,
    ),
    "tools.loadgen": RetryPolicy(
        "tools.loadgen", max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
        deadline_s=10.0,
    ),
}


def note_retry(component: str) -> None:
    """Count a retry performed outside ``call`` (e.g. the coordinator's
    backoff REQUEUE, where the 'retry' is a later scheduling wave rather
    than a blocked re-invocation) in the same metric."""
    _RETRIES.inc(component=component)


def note_give_up(component: str) -> None:
    _GIVEUPS.inc(component=component)


def retry_counts() -> dict[str, float]:
    """Per-component retry totals so far (evidence reporting)."""
    with _RETRIES._lock:
        return {k[0]: v for k, v in _RETRIES._values.items()}


def give_up_counts() -> dict[str, float]:
    with _GIVEUPS._lock:
        return {k[0]: v for k, v in _GIVEUPS._values.items()}


def policy_for(component: str) -> RetryPolicy:
    """The default policy for ``component`` (an unknown component gets a
    generic conservative policy tagged with its own name)."""
    p = DEFAULT_POLICIES.get(component)
    if p is None:
        p = dataclasses.replace(RetryPolicy(), component=component)
    return p
