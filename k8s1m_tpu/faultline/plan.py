"""Deterministic fault plans and the process-wide injector.

A ``FaultPlan`` is a seed plus a list of ``FaultSpec``s.  Each spec
targets a (component, operation) pair — ``"*"`` wildcards either — and
fires by probability (from the spec's OWN seeded stream, so two specs
never perturb each other's draws) and/or by schedule (``after`` /
``every_n`` / ``max_fires`` over that spec's matching-op counter).  The
whole decision path is pure counting + seeded PRNG: the same plan run
against the same operation sequence fires the same faults, every time —
that is what turns "we survived one kill drill" into "we survive a
specified fault distribution, reproducibly, by seed".

Components instrumented by this framework (see each call site):

====================  =====================================================
component             operations
====================  =====================================================
``store.wire``        ``range`` ``put`` ``delete`` ``txn`` ``put_batch``
                      ``bind_batch`` ``compact`` ``status`` ``watch.recv``
``watch.tier``        ``upstream.recv`` (the cache tier's store-event
                      pump: any failure kind breaks the stream — the
                      tier resumes clients from the relist diff, or
                      invalidates when the diff overflows the window);
                      ``pump.stall`` (a fan-out pump lane stalls for
                      ``delay_s`` — every kind expresses as a bounded
                      stall, the pump never dies); ``subscriber.send``
                      (one subscriber's socket: delay kinds wedge it,
                      failure kinds break it — the tier cancels that
                      watch and the client relists)
``coordinator.bind``  ``cas`` (the bind CAS, native wave and slow path)
``coordinator.watch`` ``poll`` (the intake watch drain)
``coordinator.cycle`` ``dispatch`` (the device-wave launch; ``stall``
                      opens the circuit breaker, ``slow_cycle`` shapes
                      overload latency)
``coordinator.lease`` ``tick/<identity>`` (the HA replica's election+
                      schedule tick, control/leader.HACoordinator:
                      ``kill_process`` SIGKILLs the replica — no lease
                      release, no flush, takeover on expiry;
                      ``pause`` SIGSTOPs it between the leadership
                      check and its writes — the split-brain window
                      lease-epoch fencing exists for)
``shardset.lease``    ``heartbeat/<shard>`` ``rebalance``
====================  =====================================================

Fault kinds and their contract at the hook sites:

- ``drop``           the operation's effect is discarded (a watch batch's
                     events are thrown away and the watcher is flagged
                     dropped; a heartbeat is skipped).  Never silent:
                     every hook that drops also trips the signal its
                     consumer resyncs on.
- ``delay``          sleep ``delay_s`` before the operation.
- ``disconnect``     the stream/RPC fails as a broken connection
                     (retryable ``InjectedFault``).
- ``err5xx``         the RPC fails as a server error (retryable).
- ``partial_write``  a batched write applies a prefix of the batch and
                     then fails (retryable; the batch paths are
                     idempotent-or-CAS-guarded, so the retry is safe).
- ``stale_revision`` the operation observes a stale/compacted revision
                     (a read raises the compacted signal; a bind CAS is
                     forced into conflict) — the consumer's relist /
                     requeue path must absorb it.
- ``stall``          the operation hangs past any useful deadline
                     (raised as a retryable ``InjectedFault``, like a
                     timed-out RPC).  At the cycle-dispatch hook this
                     is what trips the circuit breaker
                     (k8s1m_tpu/loadshed/breaker.py).
- ``slow_cycle``     overload-shaped latency: the operation completes
                     but takes ``delay_s`` longer — feeds the health
                     controller's cycle-p99 signal without failing
                     anything (k8s1m_tpu/loadshed/controller.py).
- ``pause``          SIGSTOP-style freeze for ``delay_s``: the process
                     keeps all its in-memory beliefs (leadership!)
                     while the world moves on.  Only the
                     ``coordinator.lease`` hook applies it; drills may
                     install ``HACoordinator.on_pause`` to advance the
                     other replicas deterministically during the freeze.
- ``kill_process``   SIGKILL-style death of the HA replica at the
                     ``coordinator.lease`` hook: no lease release, no
                     watch teardown beyond what a dead process's
                     connections get, in-flight waves die unretired —
                     the standby takes over on lease expiry.

The injector is process-global (``install_plan`` / ``active_injector``)
so subsystems need no plumbing, and seeded per spec so determinism
survives multi-component interleaving; subprocesses inherit the plan via
the ``K8S1M_FAULT_PLAN`` env var (JSON), read once at first use.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import threading
import time

from k8s1m_tpu.obs.metrics import Counter

log = logging.getLogger("k8s1m.faultline")

FAULT_KINDS = (
    "drop", "delay", "disconnect", "err5xx", "partial_write",
    "stale_revision", "stall", "slow_cycle", "pause", "kill_process",
)

_INJECTED = Counter(
    "faultline_injected_total",
    "Faults injected, by component and kind",
    ("component", "kind"),
)


class InjectedFault(Exception):
    """Raised at a hook site when a failure-kind fault fires.

    Retry layers treat it exactly like the transient wire error it
    simulates (see RetryPolicy.retryable)."""

    def __init__(self, decision: "FaultDecision"):
        super().__init__(
            f"injected {decision.kind} at "
            f"{decision.component}/{decision.op}"
        )
        self.decision = decision


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault source: where it hooks, what it does, when it fires."""

    component: str                 # e.g. "store.wire"; "*" = any
    op: str = "*"                  # e.g. "put"; "*" = any
    kind: str = "disconnect"
    probability: float = 0.0       # per matching op, from this spec's stream
    after: int = 0                 # skip the first `after` matching ops
    every_n: int = 0               # then fire every Nth matching op
    max_fires: int = 0             # 0 = unlimited
    delay_s: float = 0.0           # for kind="delay"

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (want one of {FAULT_KINDS})"
            )
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(f"probability {self.probability} not in [0, 1]")
        if self.probability == 0.0 and self.every_n <= 0:
            raise ValueError(
                "spec never fires: set probability > 0 or every_n > 0"
            )

    def matches(self, component: str, op: str) -> bool:
        return (self.component in ("*", component)) and (
            self.op in ("*", op)
        )

    def to_obj(self) -> dict:
        out = {"component": self.component, "op": self.op, "kind": self.kind}
        for f in ("probability", "after", "every_n", "max_fires", "delay_s"):
            v = getattr(self, f)
            if v:
                out[f] = v
        return out

    @classmethod
    def from_obj(cls, obj: dict) -> "FaultSpec":
        known = {f.name for f in dataclasses.fields(cls)}
        extra = set(obj) - known
        if extra:
            raise ValueError(f"unknown FaultSpec fields: {sorted(extra)}")
        return cls(**obj)


@dataclasses.dataclass(frozen=True)
class FaultDecision:
    """What fired: handed to the hook site to apply."""

    component: str
    op: str
    kind: str
    delay_s: float
    spec_index: int
    seq: int                       # this spec's fire count (1-based)


class FaultPlan:
    """A seed plus fault specs; JSON-serializable (the ``--fault-plan``
    payload): ``{"seed": 7, "faults": [{...}, ...]}``."""

    def __init__(self, faults: list[FaultSpec] | None = None, seed: int = 0):
        self.seed = int(seed)
        self.faults = list(faults or [])

    def to_json(self) -> str:
        return json.dumps(
            {"seed": self.seed, "faults": [f.to_obj() for f in self.faults]}
        )

    @classmethod
    def from_json(cls, data: "str | bytes | dict") -> "FaultPlan":
        obj = data if isinstance(data, dict) else json.loads(data)
        return cls(
            [FaultSpec.from_obj(f) for f in obj.get("faults", [])],
            seed=obj.get("seed", 0),
        )

    @classmethod
    def from_arg(cls, arg: str) -> "FaultPlan":
        """CLI form: a named plan (``NAMED_PLANS``), inline JSON, or
        ``@path`` to a JSON file."""
        named = NAMED_PLANS.get(arg)
        if named is not None:
            return named()
        if arg.startswith("@"):
            with open(arg[1:]) as f:
                return cls.from_json(f.read())
        return cls.from_json(arg)


def _watchstorm() -> FaultPlan:
    """The watchplane kill-drill plan (``watch_fanout_ab --fault-plan
    watchstorm``): upstream stream breaks (each must resolve by
    diff-replay resume, not a relist storm), fan-out pump-lane stalls,
    subscriber-socket wedges, and a few outright subscriber breaks —
    composed, deterministic by seed.  Counter units: ``upstream.recv``
    fires per received upstream batch (a coarse counter — writes
    arrive in kilo-event batches, so the break spec draws by
    probability to fire across drill scales), ``pump.stall`` per
    pump-lane wake round, ``subscriber.send`` per delivered frame."""
    return FaultPlan(
        [
            FaultSpec("watch.tier", "upstream.recv", kind="disconnect",
                      after=4, probability=0.25, max_fires=12),
            FaultSpec("watch.tier", "pump.stall", kind="delay",
                      delay_s=0.25, after=20, every_n=97, max_fires=40),
            FaultSpec("watch.tier", "subscriber.send", kind="delay",
                      delay_s=0.01, after=500, every_n=4001, max_fires=200),
            FaultSpec("watch.tier", "subscriber.send", kind="disconnect",
                      after=1000, every_n=25013, max_fires=4),
        ],
        seed=1315,
    )


# Named plans accepted anywhere a --fault-plan flag is parsed
# (FaultPlan.from_arg): drills reference a storm by name instead of
# every driver copy-pasting the same JSON.
NAMED_PLANS = {
    "watchstorm": _watchstorm,
}


class Injector:
    """Evaluates a FaultPlan; one per process (see install_plan).

    Pure-decision core: ``decide`` matches specs in plan order, counts,
    draws, and returns the first firing spec's ``FaultDecision`` (or
    None).  The only side effects are the counters, the metrics, and a
    bounded fired-log kept for determinism assertions.  Applying the
    decision — sleeping, raising, flagging a watcher dropped — is the
    hook site's job (``check`` is the synchronous convenience wrapper;
    async sites apply the decision themselves so delays don't block the
    event loop).
    """

    _LOG_CAP = 4096

    def __init__(self, plan: FaultPlan | None = None):
        self.plan = plan or FaultPlan()
        self._lock = threading.Lock()
        self._seen = [0] * len(self.plan.faults)
        self._fired = [0] * len(self.plan.faults)
        # Per-spec PRNG streams: spec i's draws depend only on (seed, i)
        # and its own matching-op count, never on other specs' traffic.
        self._rng = [
            random.Random((self.plan.seed << 16) ^ (0x9E3779B9 * (i + 1)))
            for i in range(len(self.plan.faults))
        ]
        self.fired_log: list[tuple[str, str, str, int]] = []

    def decide(self, component: str, op: str) -> FaultDecision | None:
        if not self.plan.faults:
            return None
        with self._lock:
            for i, spec in enumerate(self.plan.faults):
                if not spec.matches(component, op):
                    continue
                self._seen[i] += 1
                n = self._seen[i]
                if spec.max_fires and self._fired[i] >= spec.max_fires:
                    continue
                if n <= spec.after:
                    continue
                fire = False
                if spec.every_n > 0 and (n - spec.after) % spec.every_n == 0:
                    fire = True
                if spec.probability > 0.0:
                    # Always draw so the stream position tracks the op
                    # count (determinism does not depend on schedule hits).
                    if self._rng[i].random() < spec.probability:
                        fire = True
                if not fire:
                    continue
                self._fired[i] += 1
                d = FaultDecision(
                    component, op, spec.kind, spec.delay_s, i, self._fired[i]
                )
                if len(self.fired_log) < self._LOG_CAP:
                    self.fired_log.append((component, op, spec.kind, n))
                _INJECTED.inc(component=component, kind=spec.kind)
                log.debug("faultline: %s", d)
                return d
        return None

    def check(self, component: str, op: str) -> FaultDecision | None:
        """Synchronous hook: sleep on delay, raise on failure kinds.

        ``drop``, ``partial_write`` and ``stale_revision`` are returned
        to the caller instead — their meaning is site-specific (discard
        the batch / truncate the write / fail the CAS)."""
        d = self.decide(component, op)
        if d is None:
            return None
        if d.kind in ("delay", "slow_cycle"):
            time.sleep(d.delay_s)
            return d
        if d.kind in ("disconnect", "err5xx", "stall"):
            raise InjectedFault(d)
        return d

    async def acheck(self, component: str, op: str) -> FaultDecision | None:
        """``check`` for asyncio call sites: delays sleep on the event
        loop instead of blocking it."""
        d = self.decide(component, op)
        if d is None:
            return None
        if d.kind in ("delay", "slow_cycle"):
            import asyncio

            await asyncio.sleep(d.delay_s)
            return d
        if d.kind in ("disconnect", "err5xx", "drop", "stall"):
            raise InjectedFault(d)
        return d

    def fire_counts(self) -> dict[str, int]:
        """Total fires by kind (evidence reporting)."""
        with self._lock:
            out: dict[str, int] = {}
            for spec, n in zip(self.plan.faults, self._fired):
                out[spec.kind] = out.get(spec.kind, 0) + n
            return out

    def fire_report(self) -> list[dict]:
        """Per-spec fire counts with their targets — the evidence shape
        drills need when the same kind hooks several operations (the
        watchstorm resume-rate gate divides by UPSTREAM breaks only)."""
        with self._lock:
            return [
                {
                    "component": s.component, "op": s.op, "kind": s.kind,
                    "fires": n,
                }
                for s, n in zip(self.plan.faults, self._fired)
            ]


_NOOP = Injector()
_active: Injector = _NOOP
_env_loaded = False


def install_plan(plan: "FaultPlan | str | dict | None") -> Injector:
    """Install ``plan`` as the process's active injector (None resets
    to the no-op injector).  Returns the installed Injector."""
    global _active, _env_loaded
    _env_loaded = True           # an explicit install overrides the env
    if plan is None:
        _active = _NOOP
    else:
        if not isinstance(plan, FaultPlan):
            plan = FaultPlan.from_json(plan)
        _active = Injector(plan)
        if plan.faults:
            log.info(
                "faultline active: seed=%d, %d fault spec(s)",
                plan.seed, len(plan.faults),
            )
    return _active


def active_injector() -> Injector:
    """The process's injector; loads K8S1M_FAULT_PLAN on first use so
    subprocess topologies (harness tiers, soak benches) inherit the plan
    without each entry point growing a flag."""
    global _env_loaded, _active
    if not _env_loaded:
        _env_loaded = True
        env = os.environ.get("K8S1M_FAULT_PLAN")
        if env:
            try:
                _active = Injector(FaultPlan.from_json(env))
                log.info("faultline: plan loaded from K8S1M_FAULT_PLAN")
            except Exception:
                log.exception("faultline: bad K8S1M_FAULT_PLAN; ignoring")
    return _active


def decide(component: str, op: str) -> FaultDecision | None:
    return active_injector().decide(component, op)


def check(component: str, op: str) -> FaultDecision | None:
    return active_injector().check(component, op)


async def acheck(component: str, op: str) -> FaultDecision | None:
    return await active_injector().acheck(component, op)
